"""Console rendering of flow progress.

Capability match for the reference's ANSIProgressRenderer (reference:
node/src/main/kotlin/net/corda/node/utilities/ANSIProgressRenderer.kt:27 —
live console display of a flow's hierarchical progress). Follows the state
machine manager's bounded event feed: call poll() from any loop to print
(and get back) the lines for new events; `in_flight` snapshots the current
step path per live flow.
"""

from __future__ import annotations

import sys


class ProgressRenderer:
    def __init__(self, smm, out=None):
        self._smm = smm
        self._out = out or sys.stderr
        self._cursor = 0
        self._live: dict[bytes, tuple[str, ...]] = {}

    def poll(self) -> list[str]:
        """Consume new events; returns the lines that were rendered."""
        self._cursor, events = self._smm.changes.since(self._cursor)
        lines = []
        for event in events:
            kind = event[0]
            if kind == "add":
                self._live[event[1]] = ("started",)
                lines.append(f"[{event[1].hex()[:8]}] started")
            elif kind == "remove":
                self._live.pop(event[1], None)
                lines.append(f"[{event[1].hex()[:8]}] finished")
            elif kind == "progress":
                _, run_id, path = event
                self._live[run_id] = path
                lines.append(f"[{run_id.hex()[:8]}] " + " / ".join(path))
        for line in lines:
            print(line, file=self._out)
        return lines

    @property
    def in_flight(self) -> dict[bytes, tuple[str, ...]]:
        return dict(self._live)
