"""Shared service identities for notary clusters.

Capability match for the reference's ServiceIdentityGenerator (reference:
node/src/main/kotlin/net/corda/node/utilities/ServiceIdentityGenerator.kt —
pre-generates the CompositeKey identity a Raft notary cluster advertises, so
a signature from ANY member validates against the one service party clients
address)."""

from __future__ import annotations

from ..crypto.composite import CompositeKey
from ..crypto.keys import PublicKey
from ..crypto.party import Party


def generate_service_identity(service_name: str,
                              member_keys: list[PublicKey],
                              threshold: int = 1) -> Party:
    """The cluster's shared party: a threshold-of-n composite over member
    keys (1-of-n for a Raft cluster — consensus already guarantees the
    member that signs speaks for the quorum)."""
    if not member_keys:
        raise ValueError("a service identity needs at least one member key")
    builder = CompositeKey.Builder()
    for key in member_keys:
        builder.add_key(key)
    return Party(service_name, builder.build(threshold=threshold))
