"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the JAX kernels are backend-neutral; the CPU
backend is the conformance twin of the TPU path).

Note: this host's axon sitecustomize force-registers the TPU backend and
overrides JAX_PLATFORMS at interpreter start, so the env var alone is not
enough — we must also update jax.config after import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
