"""The async verify pipeline (crypto/async_verify.py) + its node wiring.

Covers the ISSUE acceptance list: submit/complete ordering, bounded
in-flight depth, feeder-exception propagation (a failed batch REJECTS its
flows instead of hanging them), kill-during-in-flight restore (the
at-least-once replay contract when results die with the process), the
sync fallback behind batch.async_verify = false, adaptive-crossover
bounds, and the CI smoke that runs a miniature loadtest through the
bench one-line JSON contract with the pipeline on.
"""

import json
import threading
import time

import numpy as np
import pytest

from corda_tpu.crypto.async_verify import (
    AdaptiveCrossover,
    AsyncVerifyService,
    VerifyBatchHandle,
)
from corda_tpu.crypto.keys import KeyPair, SignatureError
from corda_tpu.crypto.provider import VerifyJob
from corda_tpu.flows.api import FlowLogic, VerifySigRequest, register_flow
from corda_tpu.node.config import BatchConfig, NodeConfig
from corda_tpu.node.node import Node


# ---------------------------------------------------------------------------
# Stub verifiers (service-level tests: no node, no kernel)
# ---------------------------------------------------------------------------


class _OkVerifier:
    name = "stub-ok"

    def __init__(self):
        self.calls = 0

    def verify_batch(self, jobs):
        self.calls += 1
        return [True] * len(jobs)


class _BlockingVerifier:
    """Holds every verify_batch until released — models a device mid-kernel."""

    name = "stub-blocking"

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()

    def verify_batch(self, jobs):
        self.entered.set()
        assert self.release.wait(30.0), "test forgot to release the verifier"
        return [True] * len(jobs)


class _RaisingVerifier:
    name = "stub-raising"

    def verify_batch(self, jobs):
        raise RuntimeError("device fell off the bus")


def _jobs(n):
    return [VerifyJob(pubkey=b"\x00" * 32, message=b"\x01" * 32,
                      sig=b"\x02" * 64) for _ in range(n)]


def _drain_until(svc, want, timeout=10.0):
    """Drain handles off the completion queue until `want` arrived."""
    done = []
    deadline = time.monotonic() + timeout
    while len(done) < want and time.monotonic() < deadline:
        done.extend(svc.drain())
        time.sleep(0.002)
    assert len(done) == want, f"only {len(done)}/{want} batches completed"
    return done


# ---------------------------------------------------------------------------
# Service-level: ordering, depth, failure, close
# ---------------------------------------------------------------------------


def test_submit_drain_ordering_and_stats():
    svc = AsyncVerifyService(_OkVerifier(), depth=4)
    try:
        handles = [svc.submit(_jobs(i + 1), context=f"batch-{i}")
                   for i in range(3)]
        assert svc.in_flight == 3
        done = _drain_until(svc, 3)
        # FIFO through the single feeder: completion preserves submit order.
        assert [h.context for h in done] == ["batch-0", "batch-1", "batch-2"]
        assert done is not handles  # drain returns the same handle objects
        assert all(a is b for a, b in zip(done, handles))
        for i, h in enumerate(done):
            assert h.ok == [True] * (i + 1)
            assert h.error is None
            assert h.tier == "host"  # stub has no device_batches counter
            assert h.finished_at >= h.started_at >= 0
        assert svc.in_flight == 0
        stats = svc.stats()
        assert stats["submitted_batches"] == stats["completed_batches"] == 3
        assert stats["submitted_sigs"] == stats["completed_sigs"] == 6
        assert stats["failed_batches"] == 0
        assert stats["verify_wall_s"] >= 0.0
    finally:
        assert svc.close()


def test_bounded_depth_backpressure():
    stub = _BlockingVerifier()
    svc = AsyncVerifyService(stub, depth=2)
    try:
        svc.submit(_jobs(1), context=0)
        assert svc.can_submit()  # one slot left
        svc.submit(_jobs(1), context=1)
        assert not svc.can_submit()  # pipeline full: loop must accumulate
        assert svc.in_flight == 2
        stub.release.set()
        _drain_until(svc, 2)
        assert svc.can_submit()
        assert svc.in_flight == 0
    finally:
        stub.release.set()
        assert svc.close()


def test_feeder_exception_lands_in_handle_not_thread_death():
    svc = AsyncVerifyService(_RaisingVerifier(), depth=2)
    try:
        svc.submit(_jobs(2), context="doomed")
        (handle,) = _drain_until(svc, 1)
        assert handle.ok is None
        assert "fell off the bus" in str(handle.error)
        assert svc.stats()["failed_batches"] == 1
        # The feeder survived the exception: the next submit still works.
        svc.verifier = _OkVerifier()
        svc.submit(_jobs(1), context="after")
        (h2,) = _drain_until(svc, 1)
        assert h2.error is None and h2.ok == [True]
    finally:
        assert svc.close()


def test_close_rejects_submit_and_bounds_the_join():
    stub = _BlockingVerifier()
    svc = AsyncVerifyService(stub, depth=1)
    svc.submit(_jobs(1), context=0)
    assert stub.entered.wait(10.0)
    # Feeder is wedged inside verify_batch: close must give up on time.
    assert svc.close(timeout=0.2) is False
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(_jobs(1), context=1)
    stub.release.set()
    assert svc.close(timeout=10.0) is True


def test_depth_must_be_positive():
    with pytest.raises(ValueError, match="depth"):
        AsyncVerifyService(_OkVerifier(), depth=0)


# ---------------------------------------------------------------------------
# target_sigs: the accumulate-across-rounds gate
# ---------------------------------------------------------------------------


class _DeviceishVerifier(_OkVerifier):
    def __init__(self, min_sigs=512, ready=True):
        super().__init__()
        self.device_min_sigs = min_sigs
        self.device_gate = threading.Event()
        if ready:
            self.device_gate.set()
        self.device_batches = 0


def test_target_sigs_tracks_crossover_and_gate():
    # Host-only verifier: classic max_sigs flush policy.
    svc = AsyncVerifyService(_OkVerifier(), adaptive=False)
    assert svc.target_sigs(4096) == 4096
    # Warm device: accumulate to the crossover, not to max_sigs.
    svc = AsyncVerifyService(_DeviceishVerifier(min_sigs=512))
    assert svc.target_sigs(4096) == 512
    assert svc.target_sigs(256) == 256  # never above the batch cap
    # Cold device: batches host-route anyway, so don't starve the host tier.
    svc = AsyncVerifyService(_DeviceishVerifier(min_sigs=512, ready=False))
    assert svc.target_sigs(4096) == 4096


# ---------------------------------------------------------------------------
# AdaptiveCrossover
# ---------------------------------------------------------------------------


def _handle(n, wall_s, tier):
    h = VerifyBatchHandle(_jobs(n), context=None)
    h.started_at = 100.0
    h.finished_at = 100.0 + wall_s
    h.ok = [True] * n
    h.tier = tier
    return h


def test_adaptive_lowers_crossover_when_device_wins():
    v = _DeviceishVerifier(min_sigs=512)
    ac = AdaptiveCrossover(v)
    assert ac.enabled and ac.effective_min_sigs == 512
    # Evidence on one tier only: static policy holds.
    ac.observe(_handle(512, 0.001, "device"))
    assert v.device_min_sigs == 512
    # Device 10x faster than host: crossover walks down, bounded by FLOOR.
    for _ in range(40):
        ac.observe(_handle(512, 0.001, "device"))
        ac.observe(_handle(512, 0.010, "host"))
    assert v.device_min_sigs == AdaptiveCrossover.FLOOR
    assert ac.adjustments > 0


def test_adaptive_raises_crossover_when_host_wins_bounded():
    v = _DeviceishVerifier(min_sigs=512)
    ac = AdaptiveCrossover(v)
    for _ in range(40):
        ac.observe(_handle(512, 0.010, "device"))
        ac.observe(_handle(512, 0.001, "host"))
    assert v.device_min_sigs == ac.ceiling  # stops at the ceiling
    assert ac.ceiling >= 8 * 512


def test_adaptive_ignores_noise_samples():
    v = _DeviceishVerifier(min_sigs=512)
    ac = AdaptiveCrossover(v)
    ac.observe(_handle(8, 0.001, "device"))  # below MIN_SAMPLE_SIGS
    bad = _handle(512, 0.001, "device")
    bad.error = RuntimeError("boom")
    ac.observe(bad)  # errored batches measure nothing
    assert ac.device_rate == 0.0
    assert v.device_min_sigs == 512


def test_adaptive_disabled_for_host_only_verifier():
    ac = AdaptiveCrossover(_OkVerifier())
    assert not ac.enabled
    ac.observe(_handle(512, 0.001, "device"))
    assert ac.effective_min_sigs is None


# ---------------------------------------------------------------------------
# Node-level: flows through the pipeline, sync fallback, kill/restore
# ---------------------------------------------------------------------------


@register_flow
class SigCheckFlow(FlowLogic):
    """Parks on the verify pump for one raw signature (checkpointable
    primitives only: the kill/restore test rebuilds it from disk)."""

    def __init__(self, pubkey: bytes, message: bytes, sig_bytes: bytes):
        self.pubkey = pubkey
        self.message = message
        self.sig_bytes = sig_bytes

    def call(self):
        yield VerifySigRequest(self.pubkey, self.message, self.sig_bytes,
                               description="SigCheckFlow")
        return "verified"


def _make_node(tmp_path, name="AsyncNode", **batch_kw):
    return Node(NodeConfig(
        name=name,
        base_dir=tmp_path / name,
        network_map=tmp_path / "netmap.json",
        batch=BatchConfig(max_wait_ms=0.5, **batch_kw),
    )).start()


def _sig_args(seed=b"\x07" * 32, message=b"async-verify-me".ljust(32, b".")):
    kp = KeyPair.generate(seed)
    sig = kp.sign(message)
    return bytes(sig.by.encoded), bytes(message), bytes(sig.bytes)


def _pump(node, predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        node.run_once(timeout=0.01)
        if predicate():
            return
    raise AssertionError("node did not settle in time")


def test_async_node_verifies_and_rejects(tmp_path):
    node = _make_node(tmp_path)
    try:
        assert node.smm.async_verify is not None
        pk, msg, sig = _sig_args()
        good = node.start_flow(SigCheckFlow(pk, msg, sig))
        bad_sig = bytes([sig[0] ^ 1]) + sig[1:]
        bad = node.start_flow(SigCheckFlow(pk, msg, bad_sig))
        _pump(node, lambda: good.result.done and bad.result.done)
        assert good.result.result() == "verified"
        with pytest.raises(SignatureError):
            bad.result.result()
        stats = node.smm.async_verify.stats()
        assert stats["completed_batches"] >= 1
        assert stats["completed_sigs"] >= 2
        assert stats["in_flight"] == 0
    finally:
        node.stop()


def test_sync_mode_disables_pipeline(tmp_path):
    node = _make_node(tmp_path, name="SyncNode", async_verify=False)
    try:
        assert node.smm.async_verify is None
        pk, msg, sig = _sig_args()
        h = node.start_flow(SigCheckFlow(pk, msg, sig))
        _pump(node, lambda: h.result.done)
        assert h.result.result() == "verified"
        assert node.smm.metrics["verify_batches"] >= 1
    finally:
        node.stop()


def test_feeder_failure_rejects_flows_not_hangs(tmp_path):
    node = _make_node(tmp_path, name="FailNode")
    try:
        # Swap the verifier under the service BEFORE the lazy feeder spawns:
        # every batch now raises inside the feeder thread.
        node.smm.async_verify.verifier = _RaisingVerifier()
        pk, msg, sig = _sig_args()
        h = node.start_flow(SigCheckFlow(pk, msg, sig))
        _pump(node, lambda: h.result.done)
        # Unregistered exception types rebuild as FlowException through the
        # checkpoint-exception codec; the message survives verbatim.
        with pytest.raises(Exception, match="fell off the bus"):
            h.result.result()
        assert node.smm.async_verify.stats()["failed_batches"] == 1
        assert node.smm.in_flight_count == 0  # rejected, not parked forever
    finally:
        node.stop()


def test_kill_during_inflight_replays_at_least_once(tmp_path):
    """Results lost with the process cost a re-verify, never a lost flow:
    the park wrote no outcome, so the reborn node replays the flow and it
    re-yields the verify (the existing at-least-once contract)."""
    node = _make_node(tmp_path, name="Phoenix")
    stub = _BlockingVerifier()
    node.smm.async_verify.verifier = stub
    pk, msg, sig = _sig_args()
    node.start_flow(SigCheckFlow(pk, msg, sig))
    # Round the batch into the feeder and wedge it mid-verify.
    _pump(node, lambda: stub.entered.is_set())
    assert node.smm.async_verify.in_flight == 1
    # "Crash": the completed handle is never drained — its result dies
    # with this node object. Release first so close() can join the feeder.
    stub.release.set()
    node.stop()
    del node

    reborn = Node(NodeConfig(
        name="Phoenix",
        base_dir=tmp_path / "Phoenix",
        network_map=tmp_path / "netmap.json",
        batch=BatchConfig(max_wait_ms=0.5),
    )).start()
    try:
        assert reborn.smm.in_flight_count == 1  # checkpoint survived
        _pump(reborn, lambda: reborn.smm.in_flight_count == 0)
        assert reborn.smm.metrics["finished"] == 1
        assert reborn.smm.metrics["verify_sigs"] >= 1  # re-verified for real
    finally:
        reborn.stop()


def test_node_metrics_exposes_pipeline_stats(tmp_path):
    from corda_tpu.node.rpc import NodeRpcOps

    node = _make_node(tmp_path, name="MetricsNode")
    try:
        pk, msg, sig = _sig_args()
        h = node.start_flow(SigCheckFlow(pk, msg, sig))
        _pump(node, lambda: h.result.done)
        m = NodeRpcOps(node).node_metrics()
        av = m["async_verify"]
        assert av["depth"] == 2
        assert av["completed_batches"] >= 1
        assert "verify_drain" in m["round_stage_s"]
        assert "verify_submit" in m["round_stage_s"]
    finally:
        node.stop()

    sync_node = _make_node(tmp_path, name="MetricsSync", async_verify=False)
    try:
        assert NodeRpcOps(sync_node).node_metrics()["async_verify"] is None
    finally:
        sync_node.stop()


# ---------------------------------------------------------------------------
# CI smoke (ISSUE satellite 6): a miniature loadtest with the pipeline on,
# reported through the bench one-line JSON contract.
# ---------------------------------------------------------------------------


def test_bench_contract_smoke_with_async_loadtest(monkeypatch, capsys):
    import bench
    from test_bench_report import _stub_phases

    from corda_tpu.tools.loadtest import run_loadtest

    def mini_cluster(**kw):
        res = run_loadtest(n_tx=6, notary="validating", max_seconds=60.0,
                           batch=BatchConfig(max_wait_ms=0.5))
        return {"tx_committed": res.tx_committed,
                "tx_per_sec": res.tx_per_sec,
                "verify_batches": res.verify_batches}

    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)
    monkeypatch.setattr(bench, "bench_raft_cluster", mini_cluster)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1  # the one-line driver contract
    report = json.loads(out[0])
    assert report["metric"] == "verified_sigs_per_sec"
    cluster = report["baseline_configs"]["raft_notary_3node"]
    assert cluster["tx_committed"] == 6  # real flows really notarised
    assert cluster["verify_batches"] >= 1
