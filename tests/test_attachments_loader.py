"""Attachments-as-code loading: the AttachmentsClassLoader equivalent.

Mirrors the reference's AttachmentClassLoaderTests (reference:
core/src/test/kotlin/net/corda/core/contracts/clauses? — the classloader
suite at core/src/test, overlap rejection + class/resource loading), with
the added guarantee the reference left as a TODO: attachment code is
sandbox-vetted before execution.
"""

import pytest

from corda_tpu.contracts.attachments_loader import (
    AttachmentsModuleLoader,
    OverlappingAttachments,
    make_attachment_zip,
)
from corda_tpu.contracts.sandbox import SandboxViolation
from corda_tpu.contracts.structures import Attachment
from corda_tpu.crypto.hashes import SecureHash


class BlobAttachment(Attachment):
    def __init__(self, data: bytes):
        self._data = data

    @property
    def id(self) -> SecureHash:
        return SecureHash.sha256(self._data)

    def open(self) -> bytes:
        return self._data


GOOD_CONTRACT = b"""
from dataclasses import dataclass

from corda_tpu.contracts.structures import Contract, ContractState
from corda_tpu.contracts.dsl import require_that
from helpers import MAGIC

class ShippedContract(Contract):
    def verify(self, tx):
        with require_that() as req:
            req("exactly one output", len(tx.outputs) == 1)
            req("magic matches", MAGIC == 42)
"""

HELPERS = b"MAGIC = 42\n"


def loader_for(files, extra=()):
    blobs = [BlobAttachment(make_attachment_zip(files))]
    for f in extra:
        blobs.append(BlobAttachment(make_attachment_zip(f)))
    return AttachmentsModuleLoader(blobs)


def test_load_contract_and_sibling_import():
    loader = loader_for({"shipped.py": GOOD_CONTRACT,
                         "helpers.py": HELPERS,
                         "docs/legal.txt": b"prose"})
    contract = loader.load_contract("shipped.ShippedContract")
    assert type(contract).__name__ == "ShippedContract"
    assert loader.get_resource("docs/legal.txt") == b"prose"

    from corda_tpu.contracts.verification import TransactionForContract
    from corda_tpu.testing.dummies import DummySingleOwnerState

    tx = TransactionForContract(
        inputs=(), outputs=(DummySingleOwnerState(0),), attachments=(),
        commands=(), id=SecureHash.random(), notary=None)
    contract.verify(tx)  # one output, MAGIC == 42 -> accepts
    bad = TransactionForContract(
        inputs=(), outputs=(), attachments=(), commands=(),
        id=SecureHash.random(), notary=None)
    with pytest.raises(Exception, match="one output"):
        contract.verify(bad)


def test_overlapping_paths_rejected():
    with pytest.raises(OverlappingAttachments, match="helpers.py"):
        loader_for({"helpers.py": HELPERS},
                   extra=[{"helpers.py": b"MAGIC = 13\n"}])


def test_case_variant_paths_rejected():
    with pytest.raises(OverlappingAttachments):
        loader_for({"Helpers.py": HELPERS},
                   extra=[{"helpers.py": HELPERS}])


def test_missing_module_raises_module_not_found():
    loader = loader_for({"helpers.py": HELPERS})
    with pytest.raises(ModuleNotFoundError):
        loader.load_module("nope")


def test_hostile_attachment_rejected_at_load_time():
    evil = b"import socket\nHOST = socket.gethostname()\n"
    loader = loader_for({"evil.py": evil})
    with pytest.raises(SandboxViolation, match="socket"):
        loader.load_module("evil")


def test_hostile_builtin_rejected_at_load_time():
    evil = b"secret = open('/etc/passwd').read()\n"
    loader = loader_for({"evil.py": evil})
    with pytest.raises(SandboxViolation, match="open"):
        loader.load_module("evil")


def test_builtins_subscript_escape_rejected():
    # __builtins__['open'] would bypass every attribute/name check.
    evil = b"LEAK = __builtins__['open']\n"
    loader = loader_for({"evil.py": evil})
    with pytest.raises(SandboxViolation, match="__builtins__"):
        loader.load_module("evil")


def test_stub_shadowing_host_package_rejected():
    # Shipping an empty os.py must not whitelist the REAL os package for
    # dotted imports.
    files = {"os.py": b"STUB = 1\n",
             "evil.py": b"from os.path import exists\nHIT = exists('/')\n"}
    loader = loader_for(files)
    with pytest.raises(SandboxViolation, match="os.path"):
        loader.load_module("evil")


def test_attachment_builtins_are_restricted():
    # Defence in depth: even at runtime the module's builtins expose only
    # the sandbox whitelist — no open/eval/exec to find dynamically.
    loader = loader_for({"helpers.py": HELPERS})
    module = loader.load_module("helpers")
    b = module.__dict__["__builtins__"]
    assert "open" not in b and "eval" not in b and "exec" not in b
    assert "len" in b and "ValueError" in b


def test_runtime_import_escape_rejected():
    # Vetting is static; the __import__ shim is the runtime backstop for
    # anything reached dynamically.
    sneaky = b"from helpers import MAGIC\n"
    loader = loader_for({"sneaky.py": sneaky})  # helpers.py absent
    with pytest.raises((SandboxViolation, ModuleNotFoundError)):
        loader.load_module("sneaky")


def test_loaded_contract_is_not_a_contract_type_error():
    loader = loader_for({"helpers.py": HELPERS})
    with pytest.raises(TypeError):
        loader.load_contract("helpers.MAGIC")
