"""Attachment transfer across nodes + composite-key multi-sig cash.

Mirrors the reference's attachment-demo (reference: samples/attachment-demo/
src/main/kotlin/net/corda/attachmentdemo/AttachmentDemo.kt — a transaction
references an attachment one side doesn't have; resolution fetches it) and
BASELINE config 4 (Cash with 3-of-3 CompositeKey multi-sig fan-out verify;
composite semantics at reference core/.../crypto/CompositeKey.kt:75-81).
"""

import pytest

from corda_tpu.crypto.composite import CompositeKey
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.flows.finality import FinalityFlow
from corda_tpu.testing.dummies import DummyContract
from corda_tpu.testing.mock_network import MockNetwork


def test_attachment_fetched_during_resolution():
    """Bob receives a tx referencing an attachment only Alice has; the
    broadcast/resolve path pulls the blob over the data-vending flow."""
    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        alice = net.create_node("Alice")
        bob = net.create_node("Bob")

        blob = b"contract-legal-prose " * 100
        att_id = alice.services.storage_service.attachments \
            .import_attachment(blob)
        assert bob.services.storage_service.attachments \
            .open_attachment(att_id) is None

        builder = DummyContract.generate_initial(
            alice.identity.ref(b"\x01"), 3, notary.identity)
        builder.add_attachment(att_id)
        builder.sign_with(alice.key)
        issue_stx = builder.to_signed_transaction()
        alice.record_transaction(issue_stx)

        move = DummyContract.move(
            issue_stx.tx.out_ref(0), bob.identity.owning_key)
        move.sign_with(alice.key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        handle = alice.start_flow(FinalityFlow(
            stx, (alice.identity, bob.identity)))
        net.run_network()
        handle.result.result()

        fetched = bob.services.storage_service.attachments \
            .open_attachment(att_id)
        assert fetched is not None and fetched.open() == blob
    finally:
        net.stop_nodes()


def test_three_of_three_composite_multisig_cash():
    """A cash state owned by a 3-of-3 composite key moves only when all
    three signatures are present (BASELINE config 4 shape)."""
    from corda_tpu.contracts.structures import Command, Issued
    from corda_tpu.finance import Amount, Cash, CashState
    from corda_tpu.finance.cash import CashMove
    from corda_tpu.flows.notary import NotaryClientFlow, NotaryException
    from corda_tpu.transactions.builder import TransactionBuilder

    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary", validating=True)
        treasury = net.create_node("Treasury")

        signer_keys = [KeyPair.generate(bytes([0x61 + i]) * 32)
                       for i in range(3)]
        board = CompositeKey.Builder().add_keys(
            *[kp.public for kp in signer_keys]).build(threshold=3)

        issue = Cash.generate_issue(
            Amount(9_000, "USD"), treasury.identity.ref(b"\x01"), board,
            notary.identity)
        issue.sign_with(treasury.key)
        issue_stx = issue.to_signed_transaction()
        treasury.record_transaction(issue_stx)

        def build_move():
            tx = TransactionBuilder(notary=notary.identity)
            tx.add_input_state(issue_stx.tx.out_ref(0))
            tx.add_output_state(CashState(
                Amount(9_000, Issued(treasury.identity.ref(b"\x01"), "USD")),
                treasury.identity.owning_key))
            tx.add_command(Command(CashMove(), (board,)))
            return tx

        # Only 2 of 3 board members sign: rejected by the validating notary.
        partial = build_move()
        for kp in signer_keys[:2]:
            partial.sign_with(kp)
        understaffed = partial.to_signed_transaction(
            check_sufficient_signatures=False)
        h1 = treasury.start_flow(NotaryClientFlow(understaffed))
        net.run_network()
        with pytest.raises(Exception):
            h1.result.result()
        assert notary.uniqueness_provider.committed_count == 0

        # All 3 sign: the composite threshold is met and the move commits.
        full = build_move()
        for kp in signer_keys:
            full.sign_with(kp)
        stx = full.to_signed_transaction(check_sufficient_signatures=False)
        h2 = treasury.start_flow(NotaryClientFlow(stx))
        net.run_network()
        sig = h2.result.result()
        sig.verify(stx.id.bytes)
        assert notary.uniqueness_provider.committed_count == 1
    finally:
        net.stop_nodes()
