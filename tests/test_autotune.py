"""The autotune plane (round 21): knob registry drift guard, the
deterministic gated search, the overlay road to spawned processes, the
cross-candidate reset seams, and the bounded runtime leg.

Everything here runs against the deterministic mock response surfaces
and fake targets — no clusters, no sleeps. The real-harness wiring is
covered by the bench_autotune contract tests (test_bench_report.py) and
exercised for real by bench.py on hardware.
"""

import json

import pytest

from corda_tpu.autotune import controller, runtime, space
from corda_tpu.node.config import NodeConfig, config_overlay_from_env
from corda_tpu.obs import doctor
from corda_tpu.obs import telemetry as tm
from corda_tpu.tools import autotune as autotune_cli

# ---------------------------------------------------------------------------
# Knob registry: every entry resolves to a live lever, drift fails.
# ---------------------------------------------------------------------------


def test_registry_resolves_against_the_tree():
    assert space.validate_registry() == []


def test_registry_catches_config_drift(monkeypatch):
    """A knob whose config key stops existing must fail validation —
    the same contract as a stale trace-stage name."""
    bad = space.Knob("raft.nope", "config:raft.nope", "int",
                     1, 10, 2.0, "mul", 2, ("replicate",))
    monkeypatch.setitem(space.KNOBS, "raft.nope", bad)
    errors = space.validate_registry()
    assert any("raft.nope" in e and "no field" in e for e in errors)


def test_registry_catches_harness_and_env_drift(monkeypatch):
    gone_kwarg = space.Knob(
        "x.harness", "harness:run_ingest_sweep:no_such_kwarg", "int",
        1, 10, 2.0, "mul", 2, ())
    gone_env = space.Knob(
        "x.env", "env:CORDA_TPU_NO_SUCH_VAR:corda_tpu.node.verify_client",
        "int", 1, 10, 2.0, "mul", 2, ())
    monkeypatch.setitem(space.KNOBS, "x.harness", gone_kwarg)
    monkeypatch.setitem(space.KNOBS, "x.env", gone_env)
    errors = space.validate_registry()
    assert any("no_such_kwarg" in e for e in errors)
    assert any("CORDA_TPU_NO_SUCH_VAR" in e for e in errors)


def test_step_rules_respect_bounds_and_seeds():
    ms = space.KNOBS["batch.coalesce_ms"]  # mul knob parked at lo=0
    assert space.step_up(ms, 0.0) == 0.5   # the mul-from-zero seed
    assert space.step_up(ms, 0.5) == 1.0
    assert space.step_down(ms, 0.5) == 0.0  # back down to zero, not 0.25
    assert space.step_down(ms, 0.0) is None  # at the lower bound
    assert space.step_up(ms, 10.0) is None   # at the upper bound
    pw = space.KNOBS["raft.pipeline_window"]  # int knob mid-range
    assert space.step_up(pw, 1024) == 2048
    assert space.step_down(pw, 1024) == 512
    assert space.step_up(pw, 8192) is None
    shards = space.KNOBS["notary_shards.count"]
    assert space.step_up(shards, 4) is None  # hi clamp quantizes to int
    assert set(space.neighbors(pw, 1024)) == {2048, 512}


def test_overlay_and_env_split_by_target_kind():
    values = {"raft.pipeline_window": 2048, "batch.coalesce_ms": 0.5,
              "batch.device_min_sigs": 32,
              "sidecar.coalesce_us": 4000}
    overlay = space.overlay_for(values)
    assert overlay == {"raft": {"pipeline_window": 2048},
                       "batch": {"coalesce_ms": 0.5}}
    assert space.env_for(values) == {"CORDA_TPU_SIDECAR_MIN_SIGS": "32"}
    assert space.harness_kwargs_for(values, "run_slo_sweep") == {
        "sidecar_coalesce_us": 4000}
    assert space.harness_kwargs_for(values, "run_ingest_sweep") == {}
    toml = space.overlay_toml(values)
    assert "[raft]" in toml and "pipeline_window = 2048" in toml


# ---------------------------------------------------------------------------
# Doctor verdict -> sweep spec (the machine-readable experiment field).
# ---------------------------------------------------------------------------


def test_every_prose_rule_has_a_structured_spec():
    """RULE_SPECS mirrors RULES cause-for-cause; the prose table is
    pinned byte-identical elsewhere (test_perf_doctor), the structured
    twin must never drift from its key set."""
    assert set(doctor.RULE_SPECS) == set(doctor.RULES)
    assert set(doctor.PIPELINED_RULE_SPECS) <= set(doctor.PIPELINED_RULES)
    for spec in doctor.RULE_SPECS.values():
        assert set(spec) == {"experiment_id", "knobs", "harness"}


def test_diagnose_entries_carry_structured_experiments():
    """A real diagnose run: every bottleneck entry rides its structured
    (experiment_id, knobs, harness) spec alongside the prose."""
    signals = doctor.extract_signals({
        "metric": "verified_sigs_per_sec", "value": 1200.0,
        "e2e_stream_sigs_per_sec": 100_000.0,
        "kernel_sigs_per_sec": {"4096": 90_000.0},
        "baseline_configs": {
            "raft_validating_3node": {
                "tx_per_sec": 44.0, "p99_ms": 3800.0,
                "loadtest_sigs_per_sec": 2900.0,
                "node_stamps": {
                    "Raft0": {"device_batches": 5, "host_batches": 6}}},
            "ingest_sweep": {"peak_achieved_tx_s": 190.0}},
    })
    verdict = doctor.diagnose(signals)
    assert verdict["first_bottleneck"] == "device_occupancy"
    for entry in verdict["bottlenecks"]:
        exp = entry["experiment"]
        assert exp["experiment_id"]
        assert exp == doctor.suggest_spec(entry["cause"])
    top = verdict["bottlenecks"][0]["experiment"]
    assert top["experiment_id"] == "grow_coalesce_ladder"
    assert top["harness"] == "slo_sweep"


def test_spec_from_verdict_uses_the_structured_experiment():
    verdict = {"bottlenecks": [
        {"cause": "replicate",
         "experiment": doctor.suggest_spec("replicate")}]}
    spec = controller.spec_from_verdict(verdict)
    assert spec.experiment_id == "widen_replication_window"
    assert spec.harness == "ingest_sweep"
    assert spec.knobs == ("raft.pipeline_window", "raft.append_chunk")
    assert spec.metric == "peak_achieved_tx_s"


def test_spec_from_verdict_filters_knobs_by_harness():
    """slo_sweep-only knobs (sidecar.coalesce_us is a run_slo_sweep
    kwarg) must survive for slo_sweep specs and be dropped from
    ingest_sweep specs rather than silently no-op."""
    spec = controller.spec_from_verdict(
        {"bottlenecks": [{"cause": "device_occupancy",
                          "experiment": doctor.suggest_spec(
                              "device_occupancy")}]})
    assert spec.harness == "slo_sweep"
    assert "sidecar.coalesce_us" in spec.knobs


def test_spec_from_verdict_rejects_unsweepable_experiments():
    with pytest.raises(ValueError):
        controller.spec_from_verdict({"bottlenecks": []})
    with pytest.raises(ValueError):
        # reply's experiment is a trace profile, not a parameter sweep.
        controller.spec_from_verdict(
            {"bottlenecks": [{"cause": "reply",
                              "experiment": doctor.suggest_spec("reply")}]})


# ---------------------------------------------------------------------------
# The deterministic gated search.
# ---------------------------------------------------------------------------


def _counter(name: str) -> float:
    return tm.snapshot()["counters"][name]


def test_monotone_search_beats_the_incumbent():
    spec = controller.exploratory_spec()
    runner = controller.make_mock_runner(spec, "monotone")
    before = _counter("autotune_candidates_total")
    result = controller.run_autotune(spec, runner, budget=4, seed=0)
    assert result["candidates_evaluated"] >= 3
    assert result["improved"] is True
    assert result["best_value"] > result["baseline_value"]
    assert result["committed"] is True
    overlay = result["overlay"]
    assert overlay["values"]  # only the knobs that moved
    assert "[" in overlay["toml"]
    # Every measurement (incumbent + candidates) counted.
    assert _counter("autotune_candidates_total") - before == \
        result["candidates_evaluated"] + 1


def test_gate_rejects_regressions_and_keeps_the_incumbent():
    """On a surface where every step away from the default regresses,
    the loop must commit NOTHING: the incumbent stands, and the gate
    (not just the better-than check) records the rejections."""
    # batch.coalesce_ms defaults to its lower bound, so every proposal
    # raises it — and the regressing surface punishes that.
    spec = controller.exploratory_spec(knobs=("batch.coalesce_ms",))
    runner = controller.make_mock_runner(spec, "regressing")
    before = _counter("autotune_gate_rejections_total")
    result = controller.run_autotune(
        spec, runner, budget=4, seed=0,
        policy={"peak_achieved_tx_s": {"direction": "higher", "pct": 1.0}})
    assert result["improved"] is False
    assert result["committed"] is False
    assert result["overlay"] is None
    assert result["best_value"] == result["baseline_value"]
    assert result["gate_rejections"] > 0
    assert _counter("autotune_gate_rejections_total") > before
    assert all(s.endswith(":reject")
               for s in result["decision_sequence"])


def test_exactly_once_flip_is_a_hard_veto():
    """The cliff surface is FASTER above the default but flips
    exactly_once_all False — the gate must veto it no matter the
    speedup (a config that breaks exactly-once is wrong, not slow)."""
    spec = controller.exploratory_spec()
    runner = controller.make_mock_runner(spec, "cliff")
    result = controller.run_autotune(spec, runner, budget=4, seed=0)
    vetoed = [c for c in result["candidates"]
              if c["gate"] and c["gate"]["hard_vetoes"]]
    assert vetoed
    assert any(v["metric"] == "exactly_once_all"
               for c in vetoed for v in c["gate"]["hard_vetoes"])
    for c in vetoed:
        assert c["accepted"] is False
    # Nothing above the defaults survived: no commit.
    assert all(v <= space.KNOBS[k].default
               for k, v in result["best"]["values"].items())


def test_search_replays_bit_identical_from_its_seed():
    spec = controller.exploratory_spec()
    runs = [controller.run_autotune(
        spec, controller.make_mock_runner(spec, "noisy"),
        budget=5, seed=1234) for _ in range(2)]
    assert runs[0]["decision_sequence"] == runs[1]["decision_sequence"]
    assert json.dumps(runs[0], sort_keys=True) == \
        json.dumps(runs[1], sort_keys=True)


def test_candidate_crash_is_isolated():
    """A runner that blows up on one candidate costs that candidate
    (recorded with its error, hard-vetoed), never the search."""
    spec = controller.exploratory_spec()
    mock = controller.make_mock_runner(spec, "monotone")
    calls = []

    def flaky(vals):
        calls.append(dict(vals))
        if len(calls) == 2:  # the first non-incumbent candidate
            raise RuntimeError("cluster failed to elect")
        return mock(vals)

    result = controller.run_autotune(spec, flaky, budget=3, seed=0)
    assert result["candidates_evaluated"] == 3
    crashed = [c for c in result["candidates"]
               if c["metrics"].get("error")]
    assert len(crashed) == 1
    assert "RuntimeError" in crashed[0]["metrics"]["error"]
    assert crashed[0]["accepted"] is False
    assert any(v["metric"] == "candidate_error"
               for v in crashed[0]["gate"]["hard_vetoes"])
    # The search carried on and still found an improvement.
    assert result["improved"] is True


def test_reset_runs_before_every_measurement():
    spec = controller.exploratory_spec(knobs=("batch.coalesce_ms",))
    runner = controller.make_mock_runner(spec, "monotone")
    resets = []
    result = controller.run_autotune(
        spec, runner, budget=2, seed=0, reset=lambda: resets.append(1))
    # Incumbent + every candidate: one reset each.
    assert len(resets) == result["candidates_evaluated"] + 1


def test_reset_between_candidates_calls_reset_window():
    class Target:
        def __init__(self):
            self.resets = 0

        def reset_window(self):
            self.resets += 1

    t = Target()
    controller.reset_between_candidates(t, object(), None)
    assert t.resets == 1


# ---------------------------------------------------------------------------
# Trajectory record + gate policy.
# ---------------------------------------------------------------------------


def _mock_result(seed=7, curve="monotone"):
    spec = controller.exploratory_spec()
    return controller.run_autotune(
        spec, controller.make_mock_runner(spec, curve),
        budget=3, seed=seed,
        verdict_consumed={"source": "unit", "first_bottleneck": None,
                          "experiment_id": spec.experiment_id})


def test_autotune_record_normalizes_with_provenance():
    result = _mock_result()
    rec = doctor.normalize_record(result, source="AUTOTUNE_r21_local.json")
    assert rec["kind"] == "autotune"
    assert rec["round"] == 21
    m = rec["metrics"]
    assert m["autotune_best_value"] == result["best_value"]
    assert m["autotune_baseline_value"] == result["baseline_value"]
    assert m["autotune_candidates"] == result["candidates_evaluated"]
    assert m["autotune_exactly_once_all"] is True
    prov = rec["autotune"]
    assert prov["experiment_id"] == "explore_defaults"
    assert prov["seed"] == 7
    assert prov["decision_sequence"] == result["decision_sequence"]
    assert prov["verdict_consumed"]["source"] == "unit"
    assert len(prov["candidates"]) == len(result["candidates"])
    assert prov["committed"] == result["committed"]


def test_gate_bands_autotune_records():
    """Two autotune records in a store: a >25% drop in the committed
    best_value regresses under the default policy; the winner's
    exactly-once flag is a hard equal-direction gate."""
    good = doctor.normalize_record(_mock_result(), source="a.json")
    bad = json.loads(json.dumps(good))
    bad["metrics"]["autotune_best_value"] = \
        good["metrics"]["autotune_best_value"] * 0.5
    bad["metrics"]["autotune_exactly_once_all"] = False
    verdict = doctor.gate([good, bad], doctor.DEFAULT_POLICY)
    assert verdict["ok"] is False
    metrics = {r["metric"] for r in verdict["regressions"]}
    assert "autotune_best_value" in metrics
    assert "autotune_exactly_once_all" in metrics


# ---------------------------------------------------------------------------
# Config overlay plumbing (satellite: TOML < overlay < explicit env).
# ---------------------------------------------------------------------------


def _write_node_toml(tmp_path, body=""):
    p = tmp_path / "node.toml"
    p.write_text('name = "T"\n' + body)
    return p


def test_overlay_merges_over_toml(tmp_path, monkeypatch):
    path = _write_node_toml(tmp_path, "[raft]\npipeline_window = 64\n")
    monkeypatch.setenv("CORDA_TPU_CONFIG_OVERLAY", json.dumps(
        {"raft": {"pipeline_window": 2048},
         "batch.coalesce_ms": 1.5}))  # dotted keys nest too
    cfg = NodeConfig.load(path)
    assert cfg.raft.pipeline_window == 2048  # overlay beat the TOML
    assert cfg.batch.coalesce_ms == 1.5
    monkeypatch.delenv("CORDA_TPU_CONFIG_OVERLAY")
    assert NodeConfig.load(path).raft.pipeline_window == 64


def test_overlay_typos_fail_loud(tmp_path, monkeypatch):
    path = _write_node_toml(tmp_path)
    monkeypatch.setenv("CORDA_TPU_CONFIG_OVERLAY",
                       json.dumps({"no_such_section": {"x": 1}}))
    with pytest.raises(ValueError):
        NodeConfig.load(path)  # unknown-keys validation still applies


def test_overlay_rejects_malformed_payloads(monkeypatch):
    monkeypatch.setenv("CORDA_TPU_CONFIG_OVERLAY", "not json {")
    with pytest.raises(ValueError):
        config_overlay_from_env()
    monkeypatch.setenv("CORDA_TPU_CONFIG_OVERLAY", "[1, 2]")
    with pytest.raises(ValueError):
        config_overlay_from_env()
    monkeypatch.setenv("CORDA_TPU_CONFIG_OVERLAY",
                       json.dumps({"raft": 5, "raft.pipeline_window": 1}))
    with pytest.raises(ValueError):
        config_overlay_from_env()  # dotted key under a scalar
    monkeypatch.delenv("CORDA_TPU_CONFIG_OVERLAY")
    assert config_overlay_from_env() == {}


def test_explicit_env_still_outranks_the_overlay(tmp_path, monkeypatch):
    """Precedence top end: CORDA_TPU_FEDERATION (explicit env, read at
    its use site) beats an overlay-set [batch] sidecar address."""
    from corda_tpu.crypto.federation import FederatedVerifier
    from corda_tpu.node.node import _select_batch_verifier

    path = _write_node_toml(tmp_path)
    monkeypatch.setenv("CORDA_TPU_CONFIG_OVERLAY", json.dumps(
        {"batch": {"sidecar": "127.0.0.1:19999"}}))
    cfg = NodeConfig.load(path)
    assert cfg.batch.sidecar == "127.0.0.1:19999"  # overlay landed
    monkeypatch.setenv("CORDA_TPU_FEDERATION", "127.0.0.1:19998")
    verifier = _select_batch_verifier(cfg)
    assert isinstance(verifier, FederatedVerifier)  # env won


def test_driver_ships_overlay_to_spawned_nodes(tmp_path):
    from corda_tpu.testing import driver as drv

    class FakeHost(drv.Host):
        def __init__(self):
            self.spawned_env = None

        def mkdir(self, path):
            pass

        def write_file(self, path, text):
            pass

        def spawn(self, argv, log_path, cwd, env):
            self.spawned_env = dict(env)
            return object()

    host = FakeHost()
    d = drv.Driver(tmp_path, host=host)
    overlay = {"raft": {"pipeline_window": 2048}}
    d.start_node("Tuned", wait=False, config_overlay=overlay,
                 env_extra={"CORDA_TPU_FAULT_PLAN": "x.toml"})
    assert host.spawned_env["CORDA_TPU_CONFIG_OVERLAY"] == \
        json.dumps(overlay, sort_keys=True)
    assert host.spawned_env["CORDA_TPU_FAULT_PLAN"] == "x.toml"


# ---------------------------------------------------------------------------
# reset_window seams: no stat bleed between candidates.
# ---------------------------------------------------------------------------


def test_client_reset_window_busts_the_stats_cache():
    from corda_tpu.node.verify_client import SidecarVerifier

    sv = SidecarVerifier("127.0.0.1:1")  # never connected
    sv._server_snapshots["127.0.0.1:1"] = (1e18, {"devices": 4})
    sv.reset_window()
    assert sv._server_snapshots == {}


def test_server_reset_window_restores_the_configured_coalesce():
    from corda_tpu.crypto.sidecar import SidecarServer

    srv = SidecarServer("127.0.0.1:0", verifier=object(),
                        coalesce_us=2000, adaptive_coalesce=True)
    srv.coalesce_us = 7777  # pretend the adaptive policy wandered off
    srv._win_batches = srv._win_requests = 5
    srv._win_sigs = 500
    srv.reset_window()
    assert srv.coalesce_us == 2000
    assert (srv._win_batches, srv._win_requests, srv._win_sigs) == (0, 0, 0)


# ---------------------------------------------------------------------------
# Runtime leg: armed reverts on regression, disarmed is bit-identical.
# ---------------------------------------------------------------------------


class _Lever:
    def __init__(self):
        self.observed = []
        self.reverts = 0


def _lever_target(lever):
    return runtime.AdaptiveTarget(
        "fake", observe=lever.observed.append,
        revert=lambda: setattr(lever, "reverts", lever.reverts + 1))


def test_runtime_tuner_reverts_after_hysteresis_strikes():
    lever = _Lever()
    snaps = iter([
        {"rounds": 0, "wall_s": 0.0},
        {"rounds": 10, "wall_s": 1.0},   # score 10 -> best
        {"rounds": 12, "wall_s": 2.0},   # score 2: strike 1
        {"rounds": 14, "wall_s": 3.0},   # score 2: strike 2 -> revert
    ])
    before = _counter("autotune_reverts_total")
    tuner = runtime.RuntimeTuner(lambda: next(snaps),
                                 targets=(_lever_target(lever),),
                                 armed=True, guard_pct=25.0, hysteresis=2)
    assert tuner.step() == "idle"      # first snapshot: no delta yet
    assert tuner.step() == "observed"  # best score established
    assert tuner.step() == "observed"  # strike 1, not yet reverted
    assert lever.reverts == 0
    assert tuner.step() == "reverted"
    assert lever.reverts == 1
    assert tuner.reverted is True and tuner.armed is False
    assert _counter("autotune_reverts_total") - before == 1
    # Latched: one bad tune never oscillates.
    assert tuner.step() == "disarmed"
    assert lever.reverts == 1
    # The windows it observed fed the targets as deltas.
    assert lever.observed[0] == {"rounds": 10, "wall_s": 1.0}


def test_runtime_tuner_recovery_resets_strikes():
    lever = _Lever()
    snaps = iter([
        {"rounds": 0, "wall_s": 0.0},
        {"rounds": 10, "wall_s": 1.0},   # best 10
        {"rounds": 12, "wall_s": 2.0},   # strike 1
        {"rounds": 22, "wall_s": 3.0},   # back to 10: strikes reset
        {"rounds": 24, "wall_s": 4.0},   # strike 1 again — still armed
    ])
    tuner = runtime.RuntimeTuner(lambda: next(snaps),
                                 targets=(_lever_target(lever),),
                                 armed=True, guard_pct=25.0, hysteresis=2)
    for _ in range(5):
        tuner.step()
    assert tuner.reverted is False and tuner.armed is True
    assert lever.reverts == 0


def test_runtime_tuner_disarmed_is_bit_identical():
    calls = []
    tuner = runtime.RuntimeTuner(lambda: calls.append(1))
    assert tuner.armed is False          # off by default
    assert tuner.start() is None         # no thread
    assert tuner._thread is None
    assert tuner.step() == "disarmed"
    assert calls == []                   # snapshot never taken
    assert tuner.steps == 0


def test_runtime_targets_wrap_the_existing_policies():
    class FakeServer:
        def __init__(self):
            self.resets = 0

        def reset_window(self):
            self.resets += 1

    class FakeAdmission:
        def __init__(self):
            self.reconfigured = None

        def stats(self):
            return {"interactive_rate": 100.0, "bulk_rate": 50.0,
                    "queue_watermark": 64}

        def reconfigure(self, **kw):
            self.reconfigured = kw

    server = FakeServer()
    runtime.coalesce_target(server).revert()
    assert server.resets == 1

    adm = FakeAdmission()
    target = runtime.admission_target(adm)
    target.observe({"rounds": 1, "wall_s": 1.0})  # no calibration: no-op
    assert adm.reconfigured is None
    target.revert()
    assert adm.reconfigured == {"interactive_rate": 100.0,
                                "bulk_rate": 50.0, "queue_watermark": 64}


# ---------------------------------------------------------------------------
# The CLI.
# ---------------------------------------------------------------------------


def test_cli_validate_passes():
    assert autotune_cli.main(["--validate"]) == 0


def test_cli_mock_run_appends_and_replays(tmp_path, capsys):
    verdict = {"first_bottleneck": "replicate",
               "bottlenecks": [
                   {"cause": "replicate",
                    "experiment": doctor.suggest_spec("replicate")}]}
    vpath = tmp_path / "verdict.json"
    vpath.write_text(json.dumps(verdict))
    store = tmp_path / "TRAJECTORY.jsonl"
    out = tmp_path / "AUTOTUNE.json"

    argv = [str(vpath), "--mock", "monotone", "--budget", "3",
            "--seed", "5", "--out", str(out),
            "--trajectory", str(store)]
    assert autotune_cli.main(argv) == 0
    line = capsys.readouterr().out.strip()
    assert len(line.splitlines()) == 1  # one-JSON-line contract
    first = json.loads(line)
    assert first["experiment_id"] == "widen_replication_window"
    assert first["runner"] == {"mock": "monotone"}
    saved = json.loads(out.read_text())
    assert saved["decision_sequence"] == first["decision_sequence"]
    records = doctor.load_trajectory(str(store))
    assert len(records) == 1 and records[0]["kind"] == "autotune"

    # Replay: same seed, same surface — identical decisions, and the
    # store now bands run 2 against run 1.
    assert autotune_cli.main(argv) == 0
    second = json.loads(capsys.readouterr().out.strip())
    assert second["decision_sequence"] == first["decision_sequence"]
    assert len(doctor.load_trajectory(str(store))) == 2


def test_cli_abstained_verdict_needs_explore(tmp_path, capsys):
    vpath = tmp_path / "verdict.json"
    vpath.write_text(json.dumps({"bottlenecks": []}))
    assert autotune_cli.main([str(vpath), "--mock", "monotone",
                              "--no-append"]) == 2
    capsys.readouterr()
    assert autotune_cli.main([str(vpath), "--mock", "monotone",
                              "--explore", "--no-append"]) == 0
    result = json.loads(capsys.readouterr().out.strip())
    assert result["experiment_id"] == "explore_defaults"
