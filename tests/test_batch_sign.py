"""Round 15 — vectorized ingest plane, client side.

Two contracts under test:

* **Batch-sign parity** (crypto/batch_sign.py): the columnar signer must
  be byte-identical to the per-tx `TransactionBuilder.sign_with` loop —
  RFC 8032 signing is deterministic, so the native batch path, the
  Python fallback and the per-item reference all produce the same 64
  bytes, across widths 1/4/64 and composite owner keys; a tampered
  signature must still reject loudly downstream.

* **Multi-tx frame codec** (tools/ingest.py): `pack_frame`/`unpack_frame`
  round-trips exactly, and any damage — bad magic, truncated length or
  body, trailing junk, an oversize entry count — raises
  DeserializationError before ANY entry applies (all-or-nothing).
"""

import struct

import pytest

from corda_tpu.contracts.structures import Command
from corda_tpu.crypto import batch_sign, fast_ed25519
from corda_tpu.crypto.composite import CompositeKey
from corda_tpu.crypto.keys import DigitalSignature
from corda_tpu.serialization.codec import DeserializationError, serialize
from corda_tpu.testing.dummies import (
    DummyCreate,
    DummyMove,
    DummyMultiOwnerState,
)
from corda_tpu.testing.identities import DUMMY_NOTARY, entropy_keypair
from corda_tpu.tools.ingest import (
    FRAME_MAGIC,
    MAX_FRAME_ENTRIES,
    deserialize_corpus,
    pack_frame,
    serialize_corpus,
    unpack_frame,
)
from corda_tpu.transactions.builder import TransactionBuilder
from corda_tpu.transactions.signed import SignatureError, SignedTransaction


def _corpus_builders(n, owners, issuer, base=0):
    """n (issue, move) builder pairs in the firehose's shape: an issued
    multi-owner state spent by a width-signed move. Deterministic content
    so two calls build byte-identical wire forms."""
    issues, moves = [], []
    for i in range(n):
        issue = TransactionBuilder(notary=DUMMY_NOTARY)
        issue.add_output_state(DummyMultiOwnerState(base + i, owners))
        issue.add_command(Command(DummyCreate(), (issuer.public.composite,)))
        move = TransactionBuilder(notary=DUMMY_NOTARY)
        move.add_input_state(issue._wire_cached().out_ref(0))
        move.add_command(Command(DummyMove(), owners))
        move.add_output_state(DummyMultiOwnerState(base + i + n, owners))
        issues.append(issue)
        moves.append(move)
    return issues, moves


def _stx_bytes(builder):
    return serialize(builder.to_signed_transaction(
        check_sufficient_signatures=False)).bytes


@pytest.mark.parametrize("width", [1, 4, 64])
def test_sign_builders_byte_identical_to_sign_with(width):
    issuer = entropy_keypair(1000 + width)
    keys = [entropy_keypair(2000 + width * 100 + i) for i in range(width)]
    owners = tuple(k.public.composite for k in keys)
    n = 1 if width == 64 else 2

    # Per-tx reference: the retired prepare loop, one sign_with per sig.
    ref_issues, ref_moves = _corpus_builders(n, owners, issuer)
    for b in ref_issues:
        b.sign_with(issuer)
    for b in ref_moves:
        for k in keys:
            b.sign_with(k)

    # Columnar path: ONE sign_batch over every job in the corpus.
    issues, moves = _corpus_builders(n, owners, issuer)
    attached = batch_sign.sign_builders(
        issues + moves, [(issuer,)] * n + [keys] * n)
    assert attached == n * (1 + width)

    for ref, got in zip(ref_issues + ref_moves, issues + moves):
        assert _stx_bytes(got) == _stx_bytes(ref)
    # And the signatures actually verify, not merely match each other.
    for b in moves:
        b.to_signed_transaction(
            check_sufficient_signatures=False).check_signatures_are_valid()


def test_sign_builders_parity_composite_owner_keys():
    """A 2-of-2 composite owner: both leaves sign the move; the batch
    path must attach the same bytes in the same order as sign_with."""
    issuer = entropy_keypair(3000)
    k1, k2 = entropy_keypair(3001), entropy_keypair(3002)
    composite = CompositeKey.Builder().add_keys(
        k1.public, k2.public).build(threshold=2)
    owners = (composite,)

    ref_issues, ref_moves = _corpus_builders(2, owners, issuer, base=50)
    for b in ref_issues:
        b.sign_with(issuer)
    for b in ref_moves:
        b.sign_with(k1)
        b.sign_with(k2)

    issues, moves = _corpus_builders(2, owners, issuer, base=50)
    batch_sign.sign_builders(
        issues + moves, [(issuer,)] * 2 + [(k1, k2)] * 2)
    for ref, got in zip(ref_issues + ref_moves, issues + moves):
        assert _stx_bytes(got) == _stx_bytes(ref)
    stx = moves[0].to_signed_transaction(check_sufficient_signatures=False)
    stx.check_signatures_are_valid()
    assert not stx.get_missing_signatures() & {composite}


def test_sign_builders_skips_already_signed_key():
    issuer = entropy_keypair(4000)
    key = entropy_keypair(4001)
    owners = (key.public.composite,)
    issues, moves = _corpus_builders(1, owners, issuer, base=70)
    moves[0].sign_with(key)
    # Mirrors sign_with's dedupe guard, minus the loop's hard raise: a
    # pre-signed key costs nothing and attaches nothing.
    attached = batch_sign.sign_builders(
        issues + moves, [(issuer,), (key,)])
    assert attached == 1  # the issuer sig only
    assert len(moves[0].current_sigs) == 1


def test_tampered_batch_signature_rejects():
    issuer = entropy_keypair(5000)
    key = entropy_keypair(5001)
    owners = (key.public.composite,)
    issues, moves = _corpus_builders(1, owners, issuer, base=90)
    batch_sign.sign_builders(issues + moves, [(issuer,), (key,)])
    stx = moves[0].to_signed_transaction(check_sufficient_signatures=False)
    stx.check_signatures_are_valid()
    good = stx.sigs[0]
    bad = DigitalSignature.WithKey(
        bytes=bytes([good.bytes[0] ^ 1]) + good.bytes[1:], by=good.by)
    with pytest.raises(SignatureError):
        SignedTransaction.of(stx.tx, [bad]).check_signatures_are_valid()


def test_sign_batch_native_and_python_paths_agree(monkeypatch):
    seeds = [entropy_keypair(6000 + i).private.seed for i in range(8)]
    msgs = [bytes([i]) * 32 for i in range(8)]
    sigs = batch_sign.sign_batch(seeds, msgs)
    # Forcing the per-item fallback must not change a single byte.
    monkeypatch.setattr(batch_sign, "_NATIVE", None)
    monkeypatch.setattr(batch_sign, "_NATIVE_TRIED", True)
    assert batch_sign.sign_batch(seeds, msgs) == sigs
    assert sigs == [fast_ed25519.sign(s, m) for s, m in zip(seeds, msgs)]


def test_sign_batch_irregular_messages_fall_back_identically():
    # A non-32-byte message is ineligible for the fixed-width native
    # packing; the whole batch takes the per-item path, same bytes.
    seeds = [entropy_keypair(6100 + i).private.seed for i in range(3)]
    msgs = [b"short", b"x" * 32, b"y" * 100]
    assert batch_sign.pack_jobs(seeds, msgs) is None
    assert batch_sign.sign_batch(seeds, msgs) == [
        fast_ed25519.sign(s, m) for s, m in zip(seeds, msgs)]


def test_sign_batch_length_mismatch_raises():
    with pytest.raises(ValueError):
        batch_sign.sign_batch([b"\0" * 32], [])
    assert batch_sign.sign_batch([], []) == []


# -- multi-tx frame codec ----------------------------------------------------


def test_frame_round_trip():
    payloads = [b"", b"x", b"payload" * 97, bytes(range(256))]
    assert unpack_frame(pack_frame(payloads)) == payloads
    assert unpack_frame(pack_frame([])) == []


def test_frame_rejects_bad_magic():
    frame = pack_frame([b"abc"])
    with pytest.raises(DeserializationError, match="magic"):
        unpack_frame(b"JUNK" + frame[4:])
    with pytest.raises(DeserializationError, match="magic"):
        unpack_frame(b"")


def test_frame_rejects_truncation():
    frame = pack_frame([b"abc", b"defgh"])
    # Cut inside the last entry's body, and inside a length prefix:
    # both must reject loudly, never return the valid prefix.
    with pytest.raises(DeserializationError, match="truncated"):
        unpack_frame(frame[:-1])
    with pytest.raises(DeserializationError, match="truncated"):
        unpack_frame(frame[:8 + 2])
    # Count says 2, stream holds 1 entry.
    short = FRAME_MAGIC + struct.pack("<I", 2) + frame[8:8 + 4 + 3]
    with pytest.raises(DeserializationError, match="truncated"):
        unpack_frame(short)


def test_frame_rejects_trailing_junk():
    with pytest.raises(DeserializationError, match="trailing"):
        unpack_frame(pack_frame([b"abc"]) + b"!")


def test_frame_rejects_oversize_count():
    blob = FRAME_MAGIC + struct.pack("<I", MAX_FRAME_ENTRIES + 1)
    with pytest.raises(DeserializationError, match="oversize"):
        unpack_frame(blob)


def test_corpus_round_trip_through_frame():
    issuer = entropy_keypair(7000)
    key = entropy_keypair(7001)
    owners = (key.public.composite,)
    issues, moves = _corpus_builders(3, owners, issuer, base=110)
    batch_sign.sign_builders(issues + moves, [(issuer,)] * 3 + [(key,)] * 3)
    stxs = [b.to_signed_transaction(check_sufficient_signatures=False)
            for b in moves]
    back = deserialize_corpus(serialize_corpus(stxs))
    assert [serialize(s).bytes for s in back] == [
        serialize(s).bytes for s in stxs]
