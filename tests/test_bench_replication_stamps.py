"""Guard: the raft bench section emits the commit-pipeline stamps on the
one-line JSON contract.

CPU smoke for the driver-facing shape only: the multiprocess sweep itself is
replaced, but the stamps it would gather are built by a REAL in-process
group commit (single-member RaftMember: quorum of one) flowing through the
REAL `_member_stamp` and `bench_raft_open_loop` — so a renamed or dropped
stamp field breaks here, not in a 10-minute bench run on the driver."""

import json
import os
import sys
import types

import bench
from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.node.messaging.tcp import _Outbox
from corda_tpu.tools import loadtest
from corda_tpu.tools.loadtest import SweepResult, _member_stamp

sys.path.insert(0, os.path.dirname(__file__))
from test_bench_report import _stub_phases  # noqa: E402
from test_raft_group_commit import Net, cmd, elect, make_member  # noqa: E402

# Captured before any monkeypatching: the guard below needs the REAL
# function after _stub_phases replaces the module attribute.
_REAL_RAFT_OPEN_LOOP = bench.bench_raft_open_loop


def _real_group_commit_stamp(tmp_path) -> dict:
    """Drive the actual commit pipeline once and return its raft stamp."""
    net, t = Net(), [0.0]
    member = make_member(tmp_path, net, "Raft0", {}, lambda: t[0])
    elect(net, member, t)
    for i in range(3):
        member.submit(cmd(b"s%d" % i, b"t%d" % i, b"r%d" % i))
    member.flush_appends()
    member.quiesce_apply()  # pipelined plane: fold executor results back
    assert all(member.decided[b"r%d" % i].ok for i in range(3))
    return member.stamp()


def _burst_transport_stats() -> dict:
    """transport_stats() shape, fed by a real outbox burst."""
    outbox = _Outbox()
    outbox.append_many("peer", [(b"u1", b"f1"), (b"u2", b"f2")])
    s = outbox.stats
    return {"outbox_appends": s["appends"], "outbox_bursts": s["bursts"],
            "outbox_burst_frames": s["burst_frames"],
            "outbox_max_burst": s["max_burst"],
            "outbox_burst_avg": round(s["burst_frames"] / s["bursts"], 3),
            "bridge_flushes": 0, "bridge_flush_frames": 0,
            "bridge_max_flush": 0, "bridge_flush_avg": None}


def test_raft_bench_section_emits_replication_stamps(tmp_path, monkeypatch,
                                                     capsys):
    _stub_phases(monkeypatch)
    # _stub_phases stubs bench_raft_open_loop for the report-shape tests;
    # THIS guard exists to drive the real one (over a faked sweep), so put
    # it back.
    monkeypatch.setattr(bench, "bench_raft_open_loop", _REAL_RAFT_OPEN_LOOP)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)
    # Degraded (host-only) path: no device phases, but the raft open-loop
    # config still measures — on the real bench_raft_open_loop. One init
    # attempt: the inter-attempt flap backoff is 30 s of pure sleep.
    monkeypatch.setenv("CORDA_TPU_DEVICE_INIT_RETRIES", "1")
    monkeypatch.setattr(bench, "_device_init_with_timeout",
                        lambda *a, **k: None)
    monkeypatch.setattr(bench, "make_corpus",
                        lambda *a: ([b"pk"], [b"m"], [b"s"], [True]))

    metrics = {"verifier": "cpu",
               "raft": _real_group_commit_stamp(tmp_path),
               "transport": _burst_transport_stats()}

    def fake_sweep(rates=(60.0, 240.0, 720.0, 1800.0), n_tx=250, **kw):
        result = types.SimpleNamespace(p50_ms=5.0, p90_ms=9.0, p99_ms=20.0,
                                       tx_per_sec=30.0, committed=n_tx)
        return SweepResult(results={r: result for r in rates},
                           node_stamps={"Raft0": _member_stamp(metrics,
                                                               "cpu")})

    monkeypatch.setattr(loadtest, "run_latency_sweep", fake_sweep)

    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1  # the single-line contract survives the new keys
    report = json.loads(out[0])
    section = report["baseline_configs"]["raft_open_loop_latency"]

    # The aggregated summary names the member and carries the new stamps.
    replication = section["replication"]
    assert replication["member"] == "Raft0"
    assert replication["role"] == "leader"
    assert replication["group_commit"] is True
    assert replication["entries_per_batch"] == 3.0  # group commit visible
    assert replication["group_commits"] == 1
    # Single-member quorum: nothing crossed the wire, so RTT is honestly
    # None — the KEY must still travel (trend lines key on it).
    assert "replication_rtt_ms_avg" in replication
    assert replication["reply_coalesce_ratio"] is None  # no remote origins
    assert replication["outbox_burst_avg"] == 2.0

    # Per-member stamps keep the same fields (trend-line attribution).
    member_stamp = section["node_stamps"]["Raft0"]
    assert member_stamp["entries_per_batch"] == 3.0
    assert member_stamp["raft_role"] == "leader"
    assert member_stamp["raft"]["append_frames"] == 0  # no peers: no wire
    assert member_stamp["transport"]["outbox_bursts"] == 1
    # And the latency table is intact next to them (first rung of the
    # round-15 ladder — the vectorized ingest plane raised the defaults).
    assert section["rates"]["60_tx_s"]["p99_ms"] == 20.0


def test_sub_min_rounds_pipelined_window_abstains_not_stale_rounds():
    """Round 18 abstention fix: a short pipelined leg delta-windowed
    against its warmup baseline must report first_bottleneck None — not
    the stale "rounds" verdict carried over from the cumulative
    counters of earlier (serial) legs."""
    from corda_tpu.obs import doctor as _doctor

    cumulative = {
        "verifier": "cpu",
        "raft": {"pipeline": True, "role": "leader"},
        # 100 cumulative rounds, pump-dominated — earlier legs' shape.
        "round_stage_s": {"rounds": 100, "pump": 3.0, "fsync": 0.2},
    }
    baseline = {"round_stage_s": {"rounds": 88, "pump": 2.99,
                                  "fsync": 0.05}}
    stale = _member_stamp(cumulative, "cpu")
    assert stale["busiest_stage"] == "pump"  # the carryover trap

    windowed = _member_stamp(cumulative, "cpu", baseline=baseline)
    # 12-round window < MIN_ATTRIBUTION_ROUNDS: honest abstention.
    assert windowed["busiest_stage"] is None
    sweep = SweepResult(
        results={}, node_stamps={"Raft0": windowed},
        doctor=_doctor.stamp_attribution({"Raft0": windowed}))
    assert sweep.first_bottleneck is None


def test_delta_window_reattributes_away_from_warmup_shape():
    """With enough rounds in the window, the delta stamp names what the
    MEASURED leg was bound by, not what warmup was."""
    cumulative = {"round_stage_s": {"rounds": 100, "pump": 3.0,
                                    "fsync": 1.5}}
    baseline = {"round_stage_s": {"rounds": 40, "pump": 2.99,
                                  "fsync": 0.1}}
    assert _member_stamp(cumulative, "cpu")["busiest_stage"] == "pump"
    windowed = _member_stamp(cumulative, "cpu", baseline=baseline)
    # 60-round window: pump delta is 0.01s, fsync delta is 1.4s.
    assert windowed["busiest_stage"] == "fsync"
    # Counter resets (member restart mid-sweep) clamp to zero, never
    # negative wall time.
    reset = _member_stamp(
        {"round_stage_s": {"rounds": 25, "pump": 0.5}}, "cpu",
        baseline={"round_stage_s": {"rounds": 0, "pump": 2.0}})
    assert reset["busiest_stage"] is None


def test_replication_summary_prefers_leader_then_busiest(tmp_path):
    stamp = _real_group_commit_stamp(tmp_path)
    follower = dict(stamp, role="follower", append_frames=999)
    quiet_leader = dict(stamp, role="leader", append_frames=3)
    busy_leader = dict(stamp, role="leader", append_frames=7)
    stamps = {"Raft0": {"raft": follower, "transport": None},
              "Raft1": {"raft": quiet_leader, "transport": None},
              "Raft2": {"raft": busy_leader, "transport": None}}
    summary = bench._replication_summary(stamps)
    # A follower's frame count never outranks a leader; among two partial
    # leader views (leader change mid-sweep) the busier one wrote the log.
    assert summary["member"] == "Raft2"
    assert bench._replication_summary({}) is None
    assert bench._replication_summary(
        {"Raft0": {"raft": None, "transport": None}}) is None
