"""bench.py report assembly: one JSON line, even when a phase wedges.

The real phases need the TPU; here they are stubbed to validate the
progressive-report structure the driver depends on — including the
watchdog path added after the 2026-07-30 axon-tunnel wedge, where bench
must still print its one line with everything that finished."""

import json

import bench


def _stub_phases(monkeypatch):
    # Never run real device init in tests: on a host with a wedged
    # accelerator tunnel it burns its full timeout per call.
    monkeypatch.setattr(bench, "_device_init_with_timeout",
                        lambda *a, **k: "stub-device")
    monkeypatch.setattr(bench, "_warm_verify_kernel", lambda: None)
    monkeypatch.setattr(bench, "warm_buckets", lambda *a: None)
    monkeypatch.setattr(bench, "bench_notary_roundtrip",
                        lambda **kw: {"tx_per_sec": 100.0})
    for name in ("bench_raft_cluster", "bench_open_loop_latency",
                 "bench_raft_open_loop",  # unstubbed, this one ran a REAL
                 # multiprocess raft sweep (and now a sidecar) inside every
                 # report test — minutes of suite time measuring nothing
                 "bench_validating_flagship",  # ditto: TWO flagship runs
                 "bench_shard_scaling",  # ditto: boots up to 4 raft groups
                 "bench_multichip_scaling",  # ditto: spawns 4 mesh sidecars
                 "bench_multihost_scaling",  # ditto: spawns up to 4
                 # federated sidecar hosts + a kill leg
                 "bench_slo_sweep",  # ditto: TWO full mixed-lane sweeps
                 "bench_ingest_sweep",  # ditto: builder + replay workers
                 "bench_telemetry",  # ditto: an in-process loadtest round
                 "bench_reshard",  # ditto: live split + merge in-process nets
                 "bench_durability",  # ditto: a bitrot chaos soak + fsck
                 "bench_partition_chaos",  # ditto: a THREE-leg split-brain
                 # soak (leader cut + prevote A/B) over real TCP clusters
                 "bench_doctor",  # unstubbed, this one APPENDS to the
                 # checked-in artifacts/TRAJECTORY.jsonl from every report
                 # test — test pollution in the working tree
                 "bench_autotune",  # ditto: a real multiprocess baseline
                 # sweep plus budgeted candidate sweeps, AND it appends an
                 # autotune record to the checked-in trajectory store
                 "bench_vault_scaling",  # ditto: seeds 100k+-row sqlite
                 # vaults and replays a 100k-tx boot leg in-process
                 "bench_resolve_ids", "bench_trades", "bench_multisig",
                 "bench_partial_merkle", "bench_flow_churn"):
        monkeypatch.setattr(bench, name,
                    lambda *a, n=name, **kw: {"stub": n})
    monkeypatch.setattr(
        bench, "bench_kernel",
        lambda *a: ({4096: 1000.0}, {4096: 800.0}, {4096: 900.0},
                    {"kernel": {4096: "pallas"}, "e2e": {4096: "pallas"},
                     "e2e_devhash": {4096: "pallas"}}))
    monkeypatch.setattr(bench, "bench_stream",
                        lambda *a, **k: (1200.0, [1100.0, 1200.0], "pallas"))
    monkeypatch.setattr(bench, "bench_sha256", lambda: 5000.0)
    monkeypatch.setattr(bench, "bench_cpu_oracle", lambda *a: 250.0)


def test_report_is_one_json_line(monkeypatch, capsys):
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    report = json.loads(out[0])
    assert report["metric"] == "verified_sigs_per_sec"
    assert report["value"] == 1200.0  # stream beat the bucket numbers
    # The headline backend comes from last_backend() at stream time — None
    # here because the stream is stubbed; the per-phase stamps must still
    # carry the kernel attributions.
    assert report["backend_by_phase"]["kernel"] == {"4096": "pallas"}
    assert report["vs_baseline"] == round(1200.0 / 50_000.0, 3)
    assert report["baseline_configs"]["raft_notary_3node"] == {
        "stub": "bench_raft_cluster"}
    # The shard-scaling section must ride the DEVICE phase path too (the
    # host-only path asserts it separately) — schema parity is the
    # contract trend tooling greps against.
    assert report["baseline_configs"]["shard_scaling"] == {
        "stub": "bench_shard_scaling"}
    # Multi-chip verify-plane scaling rides the device phase path (real
    # mesh) AND the host-only path (virtual mesh) — same schema both ways.
    assert report["baseline_configs"]["multichip_scaling"] == {
        "stub": "bench_multichip_scaling"}
    # The federated verify plane (round 19) rides the device phase path
    # AND the host-only path — simulated hosts on both, same schema.
    assert report["baseline_configs"]["multihost_scaling"] == {
        "stub": "bench_multihost_scaling"}
    # The QoS SLO sweep rides the device phase path (sidecar-fed) — the
    # host-only path asserts it separately; schema parity both ways.
    assert report["baseline_configs"]["slo_sweep"] == {
        "stub": "bench_slo_sweep"}
    # The ingest-plane capability ladder (round 15) rides the device phase
    # path too — the host-only path asserts it separately.
    assert report["baseline_configs"]["ingest_sweep"] == {
        "stub": "bench_ingest_sweep"}
    # The telemetry section (round 16) rides the device phase path — the
    # host-only path asserts it separately; schema parity both ways.
    assert report["baseline_configs"]["telemetry"] == {
        "stub": "bench_telemetry"}
    # The live-reshard section (round 13) rides the device phase path —
    # the host-only path asserts it separately; schema parity both ways.
    assert report["baseline_configs"]["reshard"] == {
        "stub": "bench_reshard"}
    # The flagship is the adaptive-coalesce A/B wrapper on both paths.
    assert report["baseline_configs"]["raft_validating_3node"] == {
        "stub": "bench_validating_flagship"}
    # The durability section (round 14) rides the device phase path — the
    # host-only path asserts it separately; schema parity both ways.
    assert report["durability"] == {"stub": "bench_durability"}
    # The partition-chaos section (round 20) rides the device phase path —
    # the host-only path asserts it separately; schema parity both ways.
    assert report["partition_chaos"] == {"stub": "bench_partition_chaos"}
    # The perf-doctor section (round 17) rides the device phase path —
    # the host-only path asserts it separately; schema parity both ways.
    assert report["doctor"] == {"stub": "bench_doctor"}
    # The autotune loop (round 21) closes the doctor's loop on the device
    # phase path — the host-only path asserts it separately.
    assert report["baseline_configs"]["autotune"] == {
        "stub": "bench_autotune"}
    # The indexed vault plane (round 22) rides the device phase path at
    # full size spread — the host-only path asserts it separately.
    assert report["baseline_configs"]["vault_scaling"] == {
        "stub": "bench_vault_scaling"}
    assert "phase" not in report


def test_watchdog_timeout_still_prints_partial_report(monkeypatch, capsys):
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)

    def wedge(*a):
        raise bench.BenchTimeout("bench watchdog fired after 1s")

    # Wedge in the TAIL configs: the headline phases run first now, so a
    # watchdog fire during the slow multiprocess stretch must cost only
    # the remaining configs — never the north-star number.
    monkeypatch.setattr(bench, "bench_flow_churn", wedge)
    bench.main()
    report = json.loads(capsys.readouterr().out.strip())
    # Everything that finished is present; the wedge is attributed.
    assert report["error"] == "bench watchdog fired after 1s"
    assert report["error_phase"] == "flow_churn"
    assert report["notary_roundtrip"] == {"tx_per_sec": 100.0}
    assert report["value"] == 1200.0  # headline already landed
    assert report["baseline_configs"]["partial_merkle"] == {
        "stub": "bench_partial_merkle"}
    assert "flow_churn" not in report["baseline_configs"]


def test_degraded_mode_measures_host_configs(monkeypatch, capsys):
    # When the accelerator is unreachable (wedged tunnel), bench must still
    # measure every host-side config instead of producing nothing.
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)
    monkeypatch.setattr(bench, "_device_init_with_timeout",
                    lambda *a, **k: None)
    monkeypatch.setattr(bench, "make_corpus",
                        lambda *a: ([b"pk"], [b"m"], [b"s"], [True]))
    bench.main()
    report = json.loads(capsys.readouterr().out.strip())
    assert "accelerator unreachable" in report["error"]
    assert report["device"] == "unavailable"
    assert report["value"] == 0.0
    assert report["baseline_configs"]["raft_notary_3node"] == {
        "stub": "bench_raft_cluster"}
    assert report["baseline_configs"]["flow_churn"] == {
        "stub": "bench_flow_churn"}
    # The verifier-parameterized configs must have run WITH their kwargs
    # (a stub signature mismatch would silently exercise only error paths).
    assert report["notary_roundtrip"] == {"tx_per_sec": 100.0}
    assert report["baseline_configs"]["trader_dvp"] == {
        "stub": "bench_trades"}
    assert report["baseline_configs"]["composite_3of3"] == {
        "stub": "bench_multisig"}
    assert report["baseline_configs"]["resolve_ids"] == {
        "stub": "bench_resolve_ids"}
    assert report["baseline_configs"]["shard_scaling"] == {
        "stub": "bench_shard_scaling"}
    assert report["baseline_configs"]["multichip_scaling"] == {
        "stub": "bench_multichip_scaling"}
    assert report["baseline_configs"]["multihost_scaling"] == {
        "stub": "bench_multihost_scaling"}
    assert report["baseline_configs"]["slo_sweep"] == {
        "stub": "bench_slo_sweep"}
    assert report["baseline_configs"]["ingest_sweep"] == {
        "stub": "bench_ingest_sweep"}
    assert report["baseline_configs"]["telemetry"] == {
        "stub": "bench_telemetry"}
    assert report["baseline_configs"]["reshard"] == {
        "stub": "bench_reshard"}
    assert report["baseline_configs"]["raft_validating_3node"] == {
        "stub": "bench_validating_flagship"}
    assert report["durability"] == {"stub": "bench_durability"}
    assert report["partition_chaos"] == {"stub": "bench_partition_chaos"}
    assert report["cpu_oracle_sigs_per_sec"] == 250.0
    # The doctor runs LAST on the host-only path too — after the
    # cpu_oracle ceiling it diagnoses against.
    assert report["doctor"] == {"stub": "bench_doctor"}
    # The autotune loop rides the host-only path too — degraded hosts
    # still close the verdict -> sweep -> commit loop, same schema.
    assert report["baseline_configs"]["autotune"] == {
        "stub": "bench_autotune"}
    # The indexed vault plane rides the host-only path at trimmed sizes
    # — same schema both ways, so trend tooling greps one key.
    assert report["baseline_configs"]["vault_scaling"] == {
        "stub": "bench_vault_scaling"}


def test_watchdog_during_headline_phase_reports_honest_zero(monkeypatch,
                                                            capsys):
    """A wedge BEFORE the headline lands (kernel phase) must print the
    honest 0.0 with the wedge attributed — and the in-flight phase's wall
    time must appear in phase_seconds (the attribution the clock exists
    for)."""
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)

    def wedge(*a):
        raise bench.BenchTimeout("bench watchdog fired after 1s")

    monkeypatch.setattr(bench, "bench_kernel", wedge)
    bench.main()
    report = json.loads(capsys.readouterr().out.strip())
    assert report["error"] == "bench watchdog fired after 1s"
    assert report["error_phase"] == "kernel_buckets"
    assert report["value"] == 0.0  # headline never computed: honest zero
    assert report["notary_roundtrip"] == {"tx_per_sec": 100.0}
    assert "baseline_configs" not in report
    assert "kernel_buckets" in report["phase_seconds"]
    assert "_phase_started" not in report


def test_hard_backstop_snapshot_flushes_inflight_phase(monkeypatch):
    """The hard-watchdog snapshot path must attribute the wedged phase's
    wall time and keep the internal _phase_started marker out of the
    driver-contract JSON (the graceful path already does both)."""
    report = {"metric": "verified_sigs_per_sec", "value": 0.0,
              "phase": "kernel_buckets", "_phase_started": 0.0,
              "phase_seconds": {"warm": 2.0}}
    monkeypatch.setattr(bench.time, "monotonic", lambda: 5.0)
    snap = dict(report)
    bench._flush_inflight_phase(snap)
    snap.pop("phase", None)
    assert snap["phase_seconds"]["kernel_buckets"] == 5.0
    assert "_phase_started" not in snap
    # And the graceful main() path strips the marker on success too.
    assert "_phase_started" not in json.loads(_healthy_report_json())


def _healthy_report_json():
    import io
    from contextlib import redirect_stdout

    bench._printed = False  # earlier tests' main() already printed
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._print_report_once({"metric": "verified_sigs_per_sec",
                                  "value": 1.0})
    return buf.getvalue().strip()


def test_device_fault_mid_kernel_still_reports(monkeypatch, capsys):
    """A tunnel fault (generic exception, not BenchTimeout) inside a device
    phase must not lose the run: the phase records its error, later phases
    still measure, and exactly one JSON line prints."""
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)

    def fault(*a):
        raise RuntimeError("TPU device error - infrastructure failure")

    monkeypatch.setattr(bench, "bench_kernel", fault)
    bench.main()
    report = json.loads(capsys.readouterr().out.strip())
    assert "TPU device error" in report["kernel_error"]
    assert report["value"] == 1200.0  # stream still delivered the headline
    assert report["baseline_configs"]["flow_churn"] == {
        "stub": "bench_flow_churn"}
    assert report.get("error") is None  # isolated fault, run completed


def test_warm_fault_degrades_to_host_only(monkeypatch, capsys):
    """A device fault during warm-up means NO device phase can be trusted:
    the run degrades to the host-only sweep instead of failing slowly."""
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)
    monkeypatch.setattr(bench, "warm_buckets", lambda *a, **k: (_ for _ in ())
                        .throw(RuntimeError("UNAVAILABLE: TPU device error")))
    bench.main()
    report = json.loads(capsys.readouterr().out.strip())
    assert "faulted during warm-up" in report["error"]
    assert "UNAVAILABLE" in report["device_error"]
    assert report["baseline_configs"]["flow_churn"] == {
        "stub": "bench_flow_churn"}
    assert report["value"] == 0.0  # no device headline: honest zero


def _fake_multiprocess_result(sidecar=None, stamps=None):
    from corda_tpu.tools.loadtest import MultiProcessResult

    return MultiProcessResult(
        tx_requested=8, tx_committed=8, tx_rejected=0, width=4, clients=2,
        duration_s=1.0, wall_s=1.5, tx_per_sec=8.0, sigs_verified=32,
        sigs_per_sec=32.0, p50_ms=5.0, p99_ms=9.0,
        node_stamps=stamps if stamps is not None else {},
        sidecar=sidecar)


def test_raft_cluster_report_carries_sidecar_and_occupancy(monkeypatch):
    """The one-line-JSON contract for the sidecar rollout: BOTH the
    device-ish (sidecar=True) and the host-only default paths must emit
    the sidecar + device_occupancy keys, so trend tooling never branches
    on schema."""
    from corda_tpu.tools import loadtest

    server_stats = {"batches": 2, "sigs": 80, "cross_request_batches": 1,
                    "batch_sigs_hist": {"256": 2}}
    stamps = {"Raft0": {"device_batches": 3, "host_batches": 1},
              "Raft1": {"device_batches": 0, "host_batches": 0}}
    monkeypatch.setattr(
        loadtest, "run_loadtest_multiprocess",
        lambda **kw: _fake_multiprocess_result(
            sidecar=server_stats if kw.get("sidecar") else None,
            stamps=stamps))

    dev = bench.bench_raft_cluster(n_tx=8, sidecar=True)
    assert dev["sidecar"] == server_stats
    assert dev["device_batches"] == 3
    assert dev["host_batches"] == 1
    assert dev["device_occupancy"] == 0.75

    host = bench.bench_raft_cluster(n_tx=8)  # host-only default path
    assert "sidecar" in host and host["sidecar"] is None
    assert host["device_occupancy"] == 0.75  # same aggregation either way

    # Zero batches anywhere: occupancy is an honest 0.0, never a crash.
    monkeypatch.setattr(
        loadtest, "run_loadtest_multiprocess",
        lambda **kw: _fake_multiprocess_result(stamps={"Raft0": {}}))
    empty = bench.bench_raft_cluster(n_tx=8)
    assert empty["device_occupancy"] == 0.0
    assert empty["sidecar"] is None


def test_raft_open_loop_report_carries_sidecar_and_occupancy(monkeypatch):
    import types

    from corda_tpu.tools import loadtest

    rate_result = types.SimpleNamespace(p50_ms=4.0, p90_ms=6.0, p99_ms=8.0,
                                        tx_per_sec=30.0, committed=200)
    server_stats = {"batches": 5, "sigs": 400}

    def fake_sweep(**kw):
        return loadtest.SweepResult(
            results={30.0: rate_result},
            node_stamps={"Raft0": {"device_batches": 4, "host_batches": 4}},
            trace_snapshots=[],
            sidecar=server_stats if kw.get("sidecar") else None)

    monkeypatch.setattr(loadtest, "run_latency_sweep", fake_sweep)

    dev = bench.bench_raft_open_loop(rates=(30.0,), n_tx=200, sidecar=True)
    assert dev["sidecar"] == server_stats
    assert dev["device_occupancy"] == 0.5
    assert dev["rates"]["30_tx_s"]["p99_ms"] == 8.0

    host = bench.bench_raft_open_loop(rates=(30.0,), n_tx=200)
    assert "sidecar" in host and host["sidecar"] is None
    assert "device_occupancy" in host


def test_shard_scaling_report_contract(monkeypatch):
    """The shard_scaling section's one-line-JSON contract: one entry per
    shard count carrying throughput + the per-group ledger audit, plus the
    cross_shard_mix adversarial section whose exactly_once verdict and
    ledger-row arithmetic (expected = committed + cross_committed) must
    always be present — trend tooling greps these keys flat."""
    from corda_tpu.tools import loadtest

    calls = []

    def fake_mp(**kw):
        calls.append(kw)
        shards = kw["shards"]
        committed = kw["n_tx"]
        cross = committed // 2 if kw.get("cross_frac") else 0
        r = _fake_multiprocess_result()
        r.shards = shards
        r.tx_committed = committed
        r.tx_per_sec = 50.0 * shards  # monotone: the acceptance trend
        r.cross_requested = cross
        r.cross_committed = cross
        r.per_group_committed = [committed // shards] * shards
        r.ledger_committed = committed + cross
        r.ledger_expected = committed + cross
        r.reserved_leaked = 0
        r.exactly_once = True
        return r

    monkeypatch.setattr(loadtest, "run_loadtest_multiprocess", fake_mp)
    out = bench.bench_shard_scaling(shard_counts=(1, 2, 4), n_tx=8)

    assert set(out["shards"]) == {"1", "2", "4"}
    trend = [out["shards"][k]["tx_per_sec"] for k in ("1", "2", "4")]
    assert trend == sorted(trend)  # the acceptance bar the bench states
    for section in out["shards"].values():
        assert section["exactly_once"] is True
        assert "per_group_committed" in section
        assert "p99_ms" in section
    mix = out["cross_shard_mix"]
    assert mix["shards"] == 2 and mix["cross_frac"] == 0.5
    assert mix["ledger_committed"] == mix["ledger_expected"]
    assert mix["reserved_leaked"] == 0
    assert mix["exactly_once"] is True
    # The adversarial run actually asked for the 2PC mix.
    assert calls[-1]["cross_frac"] == 0.5 and calls[-1]["shards"] == 2
    # And every run used real OS-process groups of 1 member.
    assert all(kw["cluster_size"] == 1 for kw in calls)


def test_multichip_scaling_report_contract(monkeypatch):
    """The multichip_scaling section's one-line-JSON contract: one entry
    per mesh width carrying parity-checked sigs/s + pad/occupancy
    attribution, the flat sigs_per_sec_by_devices trend (monotone
    non-decreasing on a mesh-capable harness — the acceptance bar),
    scaling_1_to_max, and per-config error isolation. Mirrors the
    shard_scaling contract so trend tooling greps both the same way."""
    calls = []

    def fake_round(devices, **kw):
        calls.append((devices, kw))
        return {"devices": devices, "n_sigs": kw.get("n_sigs", 4096),
                "rounds": kw.get("rounds", 5),
                "sigs_per_sec": 10_000.0 * devices,  # near-linear
                "p50_ms": 8.0 / devices, "p99_ms": 12.0 / devices,
                "parity_ok": True, "client_fallbacks": 0,
                "mesh_devices": devices, "warm_error": None,
                "pad_fraction": 0.01,
                "per_device_occupancy": 0.99,
                "per_device_batch_sigs_hist": {str(4096 // devices): 5}}

    monkeypatch.setattr(bench, "_mesh_sidecar_round", fake_round)
    monkeypatch.setattr(bench, "bench_raft_cluster",
                        lambda **kw: {"stub": "flagship", **kw})

    out = bench.bench_multichip_scaling(device_counts=(1, 2, 4, 8),
                                        notary_device="accelerator",
                                        flagship=True)
    assert out["mesh"] == "device"
    assert set(out["devices"]) == {"1", "2", "4", "8"}
    trend = [out["sigs_per_sec_by_devices"][k] for k in ("1", "2", "4", "8")]
    assert trend == sorted(trend)  # monotone: the acceptance bar
    assert out["scaling_1_to_max"] == 8.0  # >= 6x at 8 vs 1 passes
    for section in out["devices"].values():
        assert section["parity_ok"] is True
        assert section["warm_error"] is None
        assert "per_device_occupancy" in section
        assert "pad_fraction" in section
    # The flagship ran the production topology fed by the widest mesh.
    flag = out["flagship_mesh_sidecar"]
    assert flag["sidecar"] is True and flag["sidecar_devices"] == 8
    assert flag["notary_device"] == "accelerator"
    # Every round targeted the requested harness.
    assert [d for d, _ in calls] == [1, 2, 4, 8]
    assert all(kw["notary_device"] == "accelerator" for _, kw in calls)

    # Host-only shape: virtual mesh, no flagship, one failing width must
    # not take down the section (per-config error isolation).
    def flaky_round(devices, **kw):
        if devices == 4:
            raise RuntimeError("mesh boot failed")
        return fake_round(devices, **kw)

    monkeypatch.setattr(bench, "_mesh_sidecar_round", flaky_round)
    host = bench.bench_multichip_scaling(device_counts=(1, 2, 4),
                                         n_sigs=1024, rounds=3)
    assert host["mesh"] == "virtual-cpu"
    assert "flagship_mesh_sidecar" not in host
    assert host["devices"]["4"] == {"error": "RuntimeError: mesh boot failed"}
    assert set(host["sigs_per_sec_by_devices"]) == {"1", "2"}
    assert "scaling_1_to_max" not in host  # max width errored: no ratio


def test_multihost_scaling_report_contract(monkeypatch):
    """The multihost_scaling section's one-line-JSON contract: one entry
    per simulated-host count carrying parity-checked sigs/s + the
    router's routing-share attribution, the flat sigs_per_sec_by_hosts
    trend (monotone non-decreasing — the acceptance bar), the host-kill
    leg's exactly_once audit, and per-width error isolation. Mirrors
    multichip_scaling so trend tooling greps both the same way."""
    calls = []

    def fake_round(hosts, **kw):
        calls.append((hosts, kw))
        out = {"hosts": hosts, "n_sigs": kw.get("n_sigs", 16),
               "workers": 2 * hosts, "batches": 40 * hosts,
               "sigs_per_sec": 120.0 * hosts,  # near-linear
               "p50_ms": 130.0, "p99_ms": 180.0, "parity_ok": True,
               "fallbacks": 0, "hedges": 0, "host_degraded": 0,
               "federation": {"routing_share_by_host": {
                   f"h{i}": round(1.0 / hosts, 4) for i in range(hosts)}}}
        if kw.get("kill_after_s") is not None:
            out["host_kill"] = {"killed_host": "h0", "exactly_once": True,
                                "answered_batches": 35,
                                "post_kill_dispatches_by_host": [0, 15],
                                "survivor_share_post_kill": 1.0,
                                "host_degraded": 1, "local_fallbacks": 1}
        return out

    monkeypatch.setattr(bench, "_federation_round", fake_round)
    out = bench.bench_multihost_scaling(host_counts=(1, 2, 4))
    # The simulated-host disclosure is part of the schema: these numbers
    # come from sidecar processes sharing one box, not a real pod.
    assert out["mesh"] == "virtual-cpu"
    assert out["simulated_hosts"] is True
    assert set(out["hosts"]) == {"1", "2", "4"}
    trend = [out["sigs_per_sec_by_hosts"][k] for k in ("1", "2", "4")]
    assert trend == sorted(trend)  # monotone: the acceptance bar
    assert out["scaling_1_to_max"] == 4.0  # >=1.7x@2, >=3x@4 passes
    for section in out["hosts"].values():
        assert section["parity_ok"] is True
        assert "routing_share_by_host" in section["federation"]
    # The kill leg ran on 2 hosts and its audit is hoisted to the top.
    assert out["host_kill"]["exactly_once"] is True
    assert out["host_kill"]["survivor_share_post_kill"] == 1.0
    assert [h for h, _ in calls] == [1, 2, 4, 2]
    assert calls[-1][1]["kill_after_s"] is not None

    # One failing width must not take down the section — and a failed
    # max width means no honest scaling ratio.
    def flaky_round(hosts, **kw):
        if hosts == 4:
            raise RuntimeError("host boot failed")
        return fake_round(hosts, **kw)

    monkeypatch.setattr(bench, "_federation_round", flaky_round)
    host = bench.bench_multihost_scaling(host_counts=(1, 2, 4),
                                         kill_leg=False)
    assert host["hosts"]["4"] == {"error": "RuntimeError: host boot failed"}
    assert set(host["sigs_per_sec_by_hosts"]) == {"1", "2"}
    assert "scaling_1_to_max" not in host
    assert "host_kill" not in host

    # A kill leg that dies mid-run is isolated the same way.
    def kill_flaky(hosts, **kw):
        if kw.get("kill_after_s") is not None:
            raise RuntimeError("kill leg wedged")
        return fake_round(hosts, **kw)

    monkeypatch.setattr(bench, "_federation_round", kill_flaky)
    out = bench.bench_multihost_scaling(host_counts=(1, 2))
    assert out["host_kill"] == {"error": "RuntimeError: kill leg wedged"}
    assert set(out["sigs_per_sec_by_hosts"]) == {"1", "2"}


def test_slo_sweep_report_contract(monkeypatch):
    """The slo_sweep section's one-line-JSON contract: per-lane p50/p99 at
    every offered load for BOTH the armed run and the no-QoS baseline,
    plus the explicit SLO verdict (interactive p99 within bound at the
    ≥5×-flagship top rate while bulk sheds, baseline collapse ratio) —
    trend tooling and the driver grep these keys flat, and the whole
    section must survive json.dumps (FirehoseResults never leak through)."""
    from corda_tpu.tools import loadtest
    from corda_tpu.tools.loadgen import FirehoseResult

    def fr(p99, shed=0, lane=""):
        return FirehoseResult(
            requested=120, committed=120 - shed, rejected=shed,
            duration_s=2.0, tx_per_sec=60.0, p50_ms=p99 / 4, p90_ms=p99 / 2,
            p99_ms=p99, width=4, sigs_signed=480, lane=lane, shed=shed)

    calls = []

    def fake_sweep(**kw):
        calls.append(kw)
        if kw["qos"]:  # armed: interactive flat, bulk shed under overload
            results = {60.0: {"interactive": fr(40.0, lane="interactive"),
                              "bulk": fr(60.0, lane="bulk")},
                       240.0: {"interactive": fr(120.0, lane="interactive"),
                               "bulk": fr(900.0, shed=35, lane="bulk")}}
            return loadtest.SweepResult(
                results=results,
                node_stamps={"Notary": {"device_batches": 0}},
                qos={"Notary": {"qos": {"interactive_flows": 30},
                                "admission": {"shed_bulk": 35}}})
        results = {60.0: {"interactive": fr(50.0, lane="interactive"),
                          "bulk": fr(55.0, lane="bulk")},
                   240.0: {"interactive": fr(2400.0, lane="interactive"),
                           "bulk": fr(2500.0, lane="bulk")}}
        return loadtest.SweepResult(results=results, node_stamps={})

    monkeypatch.setattr(loadtest, "run_slo_sweep", fake_sweep)
    out = bench.bench_slo_sweep(rates=(60.0, 240.0), slo_ms=250.0,
                                flagship_tx_s=40.0)

    json.dumps(out)  # the one-line contract: fully serializable
    # Both runs happened, armed first, over the same rates.
    assert [kw["qos"] for kw in calls] == [True, False]
    assert calls[0]["rates"] == calls[1]["rates"] == (60.0, 240.0)
    # Round 16: only the ARMED run gets the flight-recorder dump dir (the
    # baseline exists to collapse — dumping its breach would be noise),
    # and the section surfaces the dir + artifact list even when the
    # sweep result predates the telemetry fields (getattr-compat).
    assert calls[0]["flight_dir"] and "flight_dir" not in calls[1]
    assert out["flight"]["dir"] == calls[0]["flight_dir"]
    assert out["flight"]["artifacts"] == []
    assert out["cluster_telemetry"] is None
    # Per-lane percentiles at every rate, both sections.
    assert out["qos"]["240_tx_s"]["interactive"]["p99_ms"] == 120.0
    assert out["qos"]["240_tx_s"]["bulk"]["shed"] == 35
    assert out["no_qos_baseline"]["240_tx_s"]["interactive"]["p99_ms"] \
        == 2400.0
    # Member-side plane + admission stats ride along.
    assert out["member_qos"]["Notary"]["admission"]["shed_bulk"] == 35
    # The verdict: within bound at 6× flagship, bulk shed, baseline
    # collapsed 20× worse.
    v = out["verdict"]
    assert v["offered_top_tx_s"] == 240.0
    assert v["offered_over_flagship"] == 6.0
    assert v["interactive_p99_within_slo"] is True
    assert v["bulk_shed_nonzero"] is True
    assert v["interactive_vs_baseline"] == 20.0
    assert v["slo_met"] is True

    # SLO breach shape: interactive p99 over the bound flips the verdict
    # (the section reports the miss, it does not hide it).
    monkeypatch.setattr(
        loadtest, "run_slo_sweep",
        lambda **kw: loadtest.SweepResult(results={
            240.0: {"interactive": fr(900.0, lane="interactive"),
                    "bulk": fr(950.0, lane="bulk")}}))
    miss = bench.bench_slo_sweep(rates=(240.0,), slo_ms=250.0)
    assert miss["verdict"]["interactive_p99_within_slo"] is False
    assert miss["verdict"]["slo_met"] is False

    # Measured-saturation calibration rides the section: derived per-lane
    # admission rates with provenance, serializable, and honest about a
    # sweep where no rate met the SLO.
    cal = out["calibration"]
    json.dumps(cal)
    assert cal["met_slo"] is True
    assert cal["saturation_rate"] == 240.0
    assert cal["interactive_rate"] > 0 and cal["bulk_rate"] > 0
    assert miss["calibration"]["met_slo"] is False


def _fake_ingest_row(rate, achieved=None, exactly_once=True):
    return {"offered_tx_s": float(rate),
            "achieved_tx_s": achieved if achieved is not None else rate * 0.8,
            "requested": 2000, "committed": 2000, "rejected": 0,
            "duration_s": 2.0, "p50_ms": 5.0, "p99_ms": 40.0, "workers": 3,
            "frames_per_tx": 1.4, "exactly_once": exactly_once,
            "ingest": {"tx_built_per_s": 1800.0, "sigs_signed_per_s": 9000.0,
                       "serialize_ms": 120.0, "prepare_s": 1.1,
                       "bytes_written": 1 << 20, "sigs_signed": 4000,
                       "cpu_s": 3.2, "load_prepare_s": 0.4}}


def test_ingest_sweep_report_contract(monkeypatch):
    """The ingest_sweep section's one-line-JSON contract (round 15): one
    row per offered rate carrying the client-plane attribution block
    (tx_built_per_s / sigs_signed_per_s / serialize_ms / cpu_s), the
    frames-per-tx amortization, the exactly-once audit, the monotonic
    offered-rate trend, per-sub-run error isolation, and the
    first_bottleneck server-side attribution — identical schema on the
    device and host-only phase paths (both registries call this one
    function with no path-specific args)."""
    from corda_tpu.tools import loadtest

    calls = []

    def fake_sweep(**kw):
        calls.append(kw)
        if kw.get("chaos"):
            return loadtest.SweepResult(
                results={1200.0: _fake_ingest_row(1200.0)},
                node_stamps={})
        return loadtest.SweepResult(
            results={r: _fake_ingest_row(r) for r in kw["rates"]},
            node_stamps={
                "Raft0": {"busiest_stage": "fsync"},
                "Raft1": {"busiest_stage": "fsync"},
                "Raft2": {"busiest_stage": "verify"}})

    monkeypatch.setattr(loadtest, "run_ingest_sweep", fake_sweep)
    out = bench.bench_ingest_sweep(rates=(1200.0, 3600.0, 10000.0))

    json.dumps(out)  # the one-line contract: fully serializable
    # Main ladder clean, chaos leg armed with the lossy plan.
    assert calls[0].get("chaos") is None and calls[1]["chaos"] == "lossy"
    # The offered ladder is monotonic and every row carries its rate —
    # the trend tooling reads the rows in rate order.
    offered = [out["rates"][f"{r:g}_tx_s"]["offered_tx_s"]
               for r in (1200.0, 3600.0, 10000.0)]
    assert offered == sorted(offered)
    assert out["offered_rates_tx_s"] == offered
    # Client-plane attribution block rides every row.
    row = out["rates"]["3600_tx_s"]
    assert row["ingest"]["tx_built_per_s"] == 1800.0
    assert row["ingest"]["sigs_signed_per_s"] == 9000.0
    assert row["frames_per_tx"] == 1.4
    # Headline keys, flat.
    assert out["peak_offered_tx_s"] == 10000.0
    assert out["peak_achieved_tx_s"] == 8000.0
    assert out["exactly_once_all"] is True
    # Server-side attribution: the doctor's evidence-ranked verdict over
    # the member stamps (majority busiest stage wins here), with the full
    # ranked list + evidence riding under "doctor".
    assert out["first_bottleneck"] == "fsync"
    assert out["doctor"]["first_bottleneck"] == "fsync"
    top = out["doctor"]["bottlenecks"][0]
    assert top["cause"] == "fsync"
    assert top["evidence"]["busiest_stage_by_member_count"] == {
        "fsync": 2, "verify": 1}
    assert top["next_experiment"]  # every entry names its next move
    # Chaos leg verdict: exactly-once held under the lossy plan.
    assert out["chaos"]["plan"] == "lossy"
    assert out["chaos"]["exactly_once"] is True


def test_ingest_sweep_pipeline_delta_contract(monkeypatch):
    """Round 18: after the chaos leg the section runs a serial-vs-
    pipelined raft A/B at one rate and reports the committed-tx/s delta
    — the number `perfdoctor --gate` regresses on. Both legs must pin
    notary="raft" (the delta is about the commit plane, not the simple
    notary) and differ ONLY in the [raft] pipeline flag."""
    from corda_tpu.tools import loadtest

    calls = []

    def fake_sweep(**kw):
        calls.append(kw)
        if kw.get("chaos"):
            return loadtest.SweepResult(
                results={1200.0: _fake_ingest_row(1200.0)}, node_stamps={})
        rate = kw["rates"][0]
        # The pipelined leg commits 2.5x the serial leg's throughput.
        achieved = rate * (2.0 if kw.get("pipeline", True) else 0.8)
        return loadtest.SweepResult(
            results={r: _fake_ingest_row(r, achieved=achieved)
                     for r in kw["rates"]},
            node_stamps={})

    monkeypatch.setattr(loadtest, "run_ingest_sweep", fake_sweep)
    out = bench.bench_ingest_sweep(rates=(1200.0,))
    json.dumps(out)

    # Main ladder + chaos leg first, then the two delta legs.
    assert calls[1]["chaos"] == "lossy"
    serial_kw, piped_kw = calls[2], calls[3]
    assert serial_kw["pipeline"] is False and piped_kw["pipeline"] is True
    for kw in (serial_kw, piped_kw):
        assert kw["notary"] == "raft"
        assert kw["rates"] == (2400.0,)

    delta = out["pipeline_delta"]
    assert delta["notary"] == "raft"
    assert delta["rate_tx_s"] == 2400.0
    assert delta["committed_tx_s_serial"] == 1920.0
    assert delta["committed_tx_s_pipelined"] == 4800.0
    assert delta["pipeline_speedup"] == 2.5
    assert delta["exactly_once_both"] is True


def test_ingest_sweep_pipeline_delta_crash_costs_only_its_key(monkeypatch):
    from corda_tpu.tools import loadtest

    def fake_sweep(**kw):
        if "pipeline" in kw:
            raise RuntimeError("delta leg worker died")
        if kw.get("chaos"):
            return loadtest.SweepResult(
                results={1200.0: _fake_ingest_row(1200.0)}, node_stamps={})
        return loadtest.SweepResult(
            results={r: _fake_ingest_row(r) for r in kw["rates"]},
            node_stamps={})

    monkeypatch.setattr(loadtest, "run_ingest_sweep", fake_sweep)
    out = bench.bench_ingest_sweep(rates=(1200.0,))
    json.dumps(out)
    assert "RuntimeError" in out["pipeline_delta"]["error"]
    assert out["chaos"]["exactly_once"] is True  # earlier legs unharmed
    assert out["peak_achieved_tx_s"] == 960.0


def test_ingest_sweep_report_isolates_subrun_errors(monkeypatch):
    """One failed rate (dead worker, timeout) records an error row and the
    later rates still report; headline aggregates come from the rates that
    finished — and a chaos-leg crash costs only the chaos key."""
    from corda_tpu.tools import loadtest

    def fake_sweep(**kw):
        if kw.get("chaos"):
            raise RuntimeError("worker died mid-replay")
        return loadtest.SweepResult(
            results={
                1200.0: _fake_ingest_row(1200.0),
                3600.0: {"error": "TimeoutError: replay@3600 stalled",
                         "offered_tx_s": 3600.0},
                10000.0: _fake_ingest_row(10000.0)},
            node_stamps={})

    monkeypatch.setattr(loadtest, "run_ingest_sweep", fake_sweep)
    out = bench.bench_ingest_sweep(rates=(1200.0, 3600.0, 10000.0))
    json.dumps(out)
    assert "TimeoutError" in out["rates"]["3600_tx_s"]["error"]
    assert out["rates"]["10000_tx_s"]["committed"] == 2000
    assert out["peak_achieved_tx_s"] == 8000.0
    assert out["exactly_once_all"] is False  # an errored rate is not audited
    assert out["first_bottleneck"] is None  # no stamps: honest null
    assert "error" in out["chaos"]


def _fake_reshard_result(**over):
    base = dict(
        plan="reshard", epoch=1, from_shards=2, to_shards=4,
        direction="split", tx_requested=200, tx_committed=200,
        tx_rejected=0, tx_unresolved=0, exactly_once=True,
        cluster_committed=240, per_group_committed=[60, 60, 60, 60],
        reserved_leaked=0, cross_requested=40, wrong_epoch_bounces=6,
        handoff_frames=4, reshard_started_s=1.0, reshard_completed_s=1.8,
        duration_s=5.0, tx_per_sec=40.0, p50_ms=80.0, p99_ms=300.0,
        p99_before_ms=100.0, p99_during_ms=280.0, p99_after_ms=120.0,
        faults_injected={"shard.handoff:drop": 2})
    base.update(over)
    from corda_tpu.tools.loadtest import ReshardResult
    return ReshardResult(**base)


def test_reshard_report_contract(monkeypatch):
    """The reshard section's one-line-JSON contract: a chaos-armed live
    SPLIT followed by a clean MERGE back, with the headline verdict keys
    hoisted flat (exactly_once across BOTH runs, bounded wrong_epoch
    bounces, the transition window, and the before/during/after p99s that
    substantiate 'a blip, not an outage') — trend tooling greps these
    flat on the device and host-only phase paths alike."""
    from corda_tpu.tools import loadtest

    calls = []

    def fake_reshard(**kw):
        calls.append(kw)
        if kw.get("plan") == "reshard":
            return _fake_reshard_result()
        return _fake_reshard_result(
            plan=None, from_shards=4, to_shards=2, direction="merge",
            wrong_epoch_bounces=2, cross_requested=0, cluster_committed=100,
            tx_requested=100, tx_committed=100,
            per_group_committed=[50, 50, 0, 0], faults_injected={})

    monkeypatch.setattr(loadtest, "run_reshard_loadtest", fake_reshard)
    out = bench.bench_reshard(n_tx=200, rate_tx_s=80.0)

    json.dumps(out)  # the one-line contract: fully serializable
    # The split ran under the armed builtin chaos plan; the merge clean,
    # with the shard counts swapped back.
    assert calls[0]["plan"] == "reshard" and calls[0]["cross_frac"] == 0.2
    assert (calls[0]["shards"], calls[0]["to_shards"]) == (2, 4)
    assert calls[1]["plan"] is None
    assert (calls[1]["shards"], calls[1]["to_shards"]) == (4, 2)
    # Headline keys, flat.
    assert out["exactly_once"] is True
    assert out["wrong_epoch_bounces"] == 6
    assert out["handoff_frames"] == 4
    assert out["reshard_window_s"] == 0.8
    assert out["p99_before_ms"] == 100.0
    assert out["p99_during_ms"] == 280.0
    assert out["p99_after_ms"] == 120.0
    assert out["faults_injected"] == {"shard.handoff:drop": 2}
    # Full audits ride under split/merge.
    assert out["split"]["direction"] == "split"
    assert out["split"]["per_group_committed"] == [60, 60, 60, 60]
    assert out["merge"]["direction"] == "merge"

    # Either run failing the audit flips the headline verdict — the
    # section reports the miss, it does not hide it.
    monkeypatch.setattr(
        loadtest, "run_reshard_loadtest",
        lambda **kw: _fake_reshard_result(
            exactly_once=(kw.get("plan") == "reshard"),
            reshard_completed_s=None))
    bad = bench.bench_reshard(n_tx=200)
    assert bad["exactly_once"] is False
    assert bad["reshard_window_s"] is None  # never completed: honest null


def test_validating_flagship_adaptive_ab_contract(monkeypatch):
    """The flagship A/B contract: raft_validating_3node runs static-window
    then adaptive-window coalescing, the section IS the armed run (flat
    keys unchanged for trend tooling), and the static counterpart plus the
    arming verdict ride under adaptive_coalesce_ab."""
    calls = []

    def fake_cluster(**kw):
        calls.append(kw)
        adaptive = kw.get("adaptive_coalesce")
        return {"tx_per_sec": 44.0 if adaptive else 40.0, "p50_ms": 90.0,
                "p99_ms": 250.0 if adaptive else 260.0,
                "loadtest_sigs_per_sec": 700.0,
                "sidecar": {"batches": 3}}

    monkeypatch.setattr(bench, "bench_raft_cluster", fake_cluster)
    out = bench.bench_validating_flagship(verifier="jax",
                                          notary_device="accelerator")

    json.dumps(out)
    # Both runs happened, static first, on the flagship topology.
    assert [kw["adaptive_coalesce"] for kw in calls] == [False, True]
    assert all(kw["notary"] == "raft-validating" and kw["sidecar"]
               for kw in calls)
    assert all(kw["notary_device"] == "accelerator" for kw in calls)
    # The section IS the armed run; the A/B rides alongside.
    assert out["tx_per_sec"] == 44.0
    ab = out["adaptive_coalesce_ab"]
    assert ab["static"]["tx_per_sec"] == 40.0
    assert ab["adaptive"]["tx_per_sec"] == 44.0
    assert ab["tx_per_sec_ratio"] == 1.1
    assert ab["p99_ratio"] == round(250.0 / 260.0, 3)
    assert ab["adaptive_no_worse"] is True

    # Adaptive tanking throughput flips the arming verdict.
    monkeypatch.setattr(
        bench, "bench_raft_cluster",
        lambda **kw: {"tx_per_sec": 20.0 if kw.get("adaptive_coalesce")
                      else 40.0, "p50_ms": 90.0, "p99_ms": 260.0,
                      "loadtest_sigs_per_sec": 1.0, "sidecar": None})
    bad = bench.bench_validating_flagship()
    assert bad["adaptive_coalesce_ab"]["adaptive_no_worse"] is False


def test_verifier_stamp_reports_device_occupancy():
    class FakeVerifier:
        name = "jax-batch"
        device_min_sigs = 512
        device_batches = 9
        host_batches = 3

    stamp = bench._verifier_stamp(FakeVerifier())
    assert stamp["device_occupancy"] == 0.75
    FakeVerifier.device_batches = 0
    FakeVerifier.host_batches = 0
    assert bench._verifier_stamp(FakeVerifier())["device_occupancy"] == 0.0


def test_total_crash_still_prints_one_line(monkeypatch, capsys):
    """Even an exception no phase handler catches produces the one-line
    report with the crash attributed (the driver records stdout; a bare
    traceback would lose the whole run)."""
    _stub_phases(monkeypatch)
    monkeypatch.setattr(bench, "_install_watchdog", lambda *a: None)
    monkeypatch.setattr(bench, "make_corpus",
                        lambda *a: (_ for _ in ()).throw(
                            RuntimeError("totally unexpected")))
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    report = json.loads(out[0])
    assert "crash in" in report["error"]
    assert "totally unexpected" in report["error"]


def _fake_chaos_result(**over):
    from corda_tpu.tools.loadtest import ChaosResult

    base = dict(
        plan="bitrot", tx_requested=60, tx_committed=60, tx_rejected=0,
        tx_unresolved=0, exactly_once=True, cluster_committed=60,
        duration_s=4.0, tx_per_sec=15.0, p50_ms=40.0, p99_ms=220.0,
        faults_injected={"disk.corrupt:flip": 3},
        integrity_errors=3, fsck_clean=True)
    base.update(over)
    return ChaosResult(**base)


def test_durability_report_contract(monkeypatch):
    """The durability section's one-line-JSON contract (round 14): a
    bitrot chaos soak whose corruption is detected AND healed with the
    exactly-once audit intact, plus the cold detect/repair micro — with
    the verdict keys hoisted flat (exactly_once, integrity_errors,
    fsck_clean, detect_ms, repair_s) so trend tooling greps them on the
    device and host-only phase paths alike."""
    from corda_tpu.tools import loadtest

    calls = []

    def fake_chaos(**kw):
        calls.append(kw)
        return _fake_chaos_result()

    monkeypatch.setattr(loadtest, "run_chaos_loadtest", fake_chaos)
    out = bench.bench_durability(n_tx=60, micro_rows=64)

    json.dumps(out)  # the one-line contract: fully serializable
    assert calls[0]["plan"] == "bitrot"
    # Headline keys, flat.
    assert out["exactly_once"] is True
    assert out["integrity_errors"] == 3
    assert out["fsck_clean"] is True
    # The micro ran for REAL on a cold store: one corrupted row found,
    # detection latency and repair time measured, store clean afterwards.
    micro = out["detect_repair_micro"]
    assert micro["corrupt_found"] == 1
    assert micro["clean_after_repair"] is True
    assert out["detect_ms"] > 0.0
    assert out["repair_s"] > 0.0
    # Full audit rides under the sub-run key.
    assert out["bitrot_chaos"]["faults_injected"] == {"disk.corrupt:flip": 3}


def test_durability_report_isolates_subrun_errors(monkeypatch):
    """A chaos sub-run failure must cost only its own keys: the micro
    still measures (and vice versa, the section never raises)."""
    from corda_tpu.tools import loadtest

    def boom(**kw):
        raise RuntimeError("cluster failed to elect")

    monkeypatch.setattr(loadtest, "run_chaos_loadtest", boom)
    out = bench.bench_durability(n_tx=60, micro_rows=64)
    json.dumps(out)
    assert "RuntimeError" in out["bitrot_chaos"]["error"]
    assert "exactly_once" not in out  # never fabricated from a dead run
    assert out["detect_repair_micro"]["clean_after_repair"] is True
    assert out["repair_s"] > 0.0


def _doctor_report():
    # The minimal bench-report shape the doctor diagnoses: a kernel
    # ceiling, a flagship with low occupancy, and an ingest peak.
    return {
        "metric": "verified_sigs_per_sec", "value": 1200.0,
        "e2e_stream_sigs_per_sec": 100_000.0,
        "kernel_sigs_per_sec": {"4096": 90_000.0},
        "baseline_configs": {
            "raft_validating_3node": {
                "tx_per_sec": 44.0, "p99_ms": 3800.0,
                "loadtest_sigs_per_sec": 2900.0,
                "node_stamps": {
                    "Raft0": {"device_batches": 5, "host_batches": 6}}},
            "ingest_sweep": {"peak_achieved_tx_s": 190.0}},
    }


def test_doctor_section_contract(monkeypatch, tmp_path):
    """The doctor section's one-line-JSON contract (round 17): the
    verdict (roofline + ranked bottlenecks), the normalized trajectory
    record, and the trajectory block (path, delta vs the last record of
    this kind, gate) — serializable, and actually appended to the store
    the env var points at (never the checked-in one from a test)."""
    store = tmp_path / "TRAJECTORY.jsonl"
    monkeypatch.setenv("CORDA_TPU_TRAJECTORY", str(store))
    out = bench.bench_doctor(_doctor_report())

    json.dumps(out)  # the one-line contract: fully serializable
    v = out["verdict"]
    assert v["first_bottleneck"] == "device_occupancy"
    assert v["roofline"]["ceiling_sigs_per_sec"] == 100_000.0
    assert v["roofline"]["gap_factor"] == round(100_000.0 / 2900.0, 2)
    assert v["bottlenecks"][0]["next_experiment"]
    rec = out["record"]
    assert rec["kind"] == "bench_report"
    assert rec["metrics"]["flagship_tx_per_sec"] == 44.0
    assert rec["metrics"]["ingest_peak_achieved_tx_s"] == 190.0
    # First run: appended, no predecessor of this kind to diff against.
    assert out["trajectory"]["appended"] is True
    assert out["trajectory"]["delta"] is None
    assert out["trajectory"]["gate"]["ok"] is True
    assert store.exists()

    # Second run, 25% p99 regression: the delta and the gate both say so
    # in the section — and the run still appends (the gate INFORMS the
    # bench report; perfdoctor --gate is where it blocks).
    worse = _doctor_report()
    worse["baseline_configs"]["raft_validating_3node"]["p99_ms"] = 4750.0
    out2 = bench.bench_doctor(worse)
    json.dumps(out2)
    assert out2["trajectory"]["delta"]["metrics"][
        "flagship_p99_ms"]["change_pct"] == 25.0
    gate = out2["trajectory"]["gate"]
    assert gate["ok"] is False
    assert gate["regressions"][0]["metric"] == "flagship_p99_ms"
    assert out2["trajectory"]["appended"] is True
    assert len(store.read_text().splitlines()) == 2


def test_doctor_section_isolates_store_errors(monkeypatch, tmp_path):
    """An unwritable/corrupt trajectory store costs the trajectory block
    only — the verdict and record still land in the report (the doctor
    section never takes down the one-line contract)."""
    blocker = tmp_path / "occupied"
    blocker.write_text("not json {")
    monkeypatch.setenv("CORDA_TPU_TRAJECTORY", str(blocker))
    out = bench.bench_doctor(_doctor_report())
    json.dumps(out)
    assert out["verdict"]["first_bottleneck"] == "device_occupancy"
    assert out["record"]["kind"] == "bench_report"
    assert out["trajectory"]["appended"] is False
    assert "ValueError" in out["trajectory"]["error"]


def _stub_autotune_baseline(monkeypatch, verdict):
    """Wire bench_autotune to a stubbed baseline sweep (one healthy row
    whose metrics sit exactly on the mock surface's default point) and
    the deterministic monotone mock runner — no real clusters."""
    import types

    from corda_tpu.autotune import controller
    from corda_tpu.tools import loadtest

    fake = types.SimpleNamespace(
        results={2400.0: {"achieved_tx_s": 1000.0, "p99_ms": 50.0,
                          "exactly_once": True}},
        doctor=verdict, first_bottleneck=verdict.get("first_bottleneck"))
    monkeypatch.setattr(loadtest, "run_ingest_sweep", lambda **kw: fake)
    spec = controller.spec_from_verdict(verdict)
    mock = controller.make_mock_runner(spec, "monotone")
    monkeypatch.setattr(controller, "make_ingest_runner",
                        lambda **kw: mock)


def test_autotune_section_contract(monkeypatch, tmp_path):
    """The autotune section's contract (round 21): the loop consumes the
    baseline run's REAL doctor verdict (structured experiment spec, not
    prose), evaluates its gated candidates, reports best vs baseline on
    the swept metric, and appends one ``autotune`` provenance record to
    the store CORDA_TPU_TRAJECTORY points at."""
    from corda_tpu.obs import doctor

    verdict = {"first_bottleneck": "seal",
               "bottlenecks": [{"cause": "seal",
                                "experiment": doctor.suggest_spec("seal")}]}
    _stub_autotune_baseline(monkeypatch, verdict)
    store = tmp_path / "TRAJECTORY.jsonl"
    monkeypatch.setenv("CORDA_TPU_TRAJECTORY", str(store))

    out = bench.bench_autotune(budget=3, seed=7)
    json.dumps(out)  # the one-line contract: fully serializable
    # The sweep came from the verdict's structured experiment, not a
    # fallback: seal implicates the group-commit density levers.
    assert out["experiment_id"] == "raise_group_commit_density"
    assert out["cause"] == "seal"
    assert out["first_bottleneck"] == "seal"
    assert out["knobs"] == ["batch.coalesce_ms", "raft.append_chunk"]
    assert out["candidates_evaluated"] == 3
    # The monotone surface rewards stepping up: the loop must beat the
    # hand-tuned default and commit the winner as a TOML overlay.
    assert out["improved"] is True
    assert out["best_value"] > out["baseline_value"] == 1000.0
    assert out["committed_values"]
    assert "[" in out["committed_overlay"]  # rendered TOML section
    assert len(out["decision_sequence"]) == 3
    assert all(s.endswith(("accept", "reject"))
               for s in out["decision_sequence"])
    # Provenance landed in the env-pointed store, kind "autotune".
    assert out["trajectory"]["appended"] is True
    lines = store.read_text().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["kind"] == "autotune"
    assert rec["autotune"]["experiment_id"] == "raise_group_commit_density"
    assert rec["metrics"]["autotune_best_value"] == out["best_value"]


def test_autotune_section_isolates_store_errors(monkeypatch, tmp_path):
    """An unwritable trajectory store costs the append only — the
    section's sweep results still land (same isolation as the doctor
    section). Unlike bench_doctor, the autotune append never READS the
    store, so the failure mode is a write error, not corrupt JSON."""
    from corda_tpu.obs import doctor

    verdict = {"first_bottleneck": "seal",
               "bottlenecks": [{"cause": "seal",
                                "experiment": doctor.suggest_spec("seal")}]}
    _stub_autotune_baseline(monkeypatch, verdict)
    blocker = tmp_path / "occupied"
    blocker.write_text("i am a file, not a directory")
    monkeypatch.setenv("CORDA_TPU_TRAJECTORY",
                       str(blocker / "TRAJECTORY.jsonl"))

    out = bench.bench_autotune(budget=2, seed=7)
    json.dumps(out)
    assert out["best_value"] >= out["baseline_value"]
    assert out["candidates_evaluated"] == 2
    assert out["trajectory"]["appended"] is False
    assert "Error" in out["trajectory"]["error"]
