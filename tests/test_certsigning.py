"""Certificate-signing (network permissioning) tests.

Mirrors the reference's certsigning flow (reference: node/.../utilities/
certsigning/CertificateSigner.kt buildKeyStore — CSR, slow-poll, install)
against the in-repo authority server.
"""

import threading

import pytest

# The whole module is a capability test of the OpenSSL-backed cert path:
# without the wheel it is a clean SKIP (reason in the report), not a
# collection ERROR polluting the suite's pass/fail signal.
pytest.importorskip(
    "cryptography",
    reason="the 'cryptography' wheel is not installed on this interpreter "
           "— certificate signing requires it (declared dependency)")

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from corda_tpu.crypto.certsigning import (
    CertificateRequestRejected,
    CertificateSigner,
    CertificateSigningServer,
    HttpCertificateSigningService,
)
from corda_tpu.crypto.x509 import ensure_dev_ca


@pytest.fixture()
def authority(tmp_path):
    ca_cert, ca_key = ensure_dev_ca(tmp_path / "shared")
    server = CertificateSigningServer(ca_cert, ca_key)
    yield server
    server.stop()


def make_csr(cn="TestNode"):
    key = ec.generate_private_key(ec.SECP256R1())
    csr = (x509.CertificateSigningRequestBuilder()
           .subject_name(x509.Name(
               [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
           .sign(key, hashes.SHA256()))
    return key, csr.public_bytes(serialization.Encoding.DER)


def test_doorman_approval_workflow(authority):
    service = HttpCertificateSigningService(authority.url)
    _, csr_der = make_csr("Alice Corp")
    request_id = service.submit_request(csr_der)

    # pending: poll returns None; the operator sees the request
    assert service.retrieve_certificates(request_id) is None
    assert authority.pending_requests() == {request_id: "Alice Corp"}

    authority.approve(request_id)
    chain = service.retrieve_certificates(request_id)
    assert chain is not None and len(chain) == 2
    leaf, root = chain[0], chain[-1]
    cn = leaf.subject.get_attributes_for_oid(NameOID.COMMON_NAME)[0].value
    assert cn == "Alice Corp"
    # leaf really is signed by the root CA
    root.public_key().verify(
        leaf.signature, leaf.tbs_certificate_bytes,
        ec.ECDSA(leaf.signature_hash_algorithm))


def test_rejection_raises(authority):
    service = HttpCertificateSigningService(authority.url)
    _, csr_der = make_csr()
    request_id = service.submit_request(csr_der)
    authority.reject(request_id)
    with pytest.raises(CertificateRequestRejected):
        service.retrieve_certificates(request_id)


def test_malformed_csr_rejected_at_submit(authority):
    service = HttpCertificateSigningService(authority.url)
    import urllib.error

    with pytest.raises(urllib.error.HTTPError):
        service.submit_request(b"this is not a CSR")


def test_certificate_signer_end_to_end(tmp_path, authority):
    authority.auto_approve = True
    service = HttpCertificateSigningService(authority.url)
    signer = CertificateSigner(tmp_path / "node", "Bank of TPU", service,
                               poll_interval=0.01)
    paths = signer.build_key_store(timeout=10)
    for p in paths.values():
        assert p.exists()
    leaf = x509.load_pem_x509_certificate(paths["cert"].read_bytes())
    assert leaf.subject.get_attributes_for_oid(
        NameOID.COMMON_NAME)[0].value == "Bank of TPU"
    # key on disk matches the certified public key
    key = serialization.load_pem_private_key(
        paths["key"].read_bytes(), password=None)
    assert key.public_key().public_numbers() \
        == leaf.public_key().public_numbers()
    # idempotent: a restart finds the material and submits nothing new
    before = dict(authority._issued)
    paths2 = signer.build_key_store(timeout=1)
    assert paths2 == paths and authority._issued == before


def test_slow_doorman_approval_completes(tmp_path, authority):
    """The signer's poll loop survives an authority that approves late
    (the reference's 1-minute slow-poll, scaled down)."""
    service = HttpCertificateSigningService(authority.url)
    signer = CertificateSigner(tmp_path / "node", "Slow Corp", service,
                               poll_interval=0.02)

    def approve_soon():
        import time

        for _ in range(200):
            pending = authority.pending_requests()
            if pending:
                authority.approve(next(iter(pending)))
                return
            time.sleep(0.01)

    t = threading.Thread(target=approve_soon)
    t.start()
    paths = signer.build_key_store(timeout=10)
    t.join(timeout=5)
    assert paths["cert"].exists()
