"""Measured recovery under injected faults (ISSUE round 7).

Tier-1 tier: the device-degrade seam (gate install, host re-verify, cooldown
re-probe) in isolation — fast and deterministic. The cluster soaks (leader
kill mid-burst, lossy transport) boot real TCP+sqlite raft nodes and are
marked slow.
"""

import time

import numpy as np
import pytest

from corda_tpu.crypto.provider import (
    CpuVerifier, DeviceRoutedVerifier, VerifyJob, degrade_device,
)
from corda_tpu.testing import faults


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


class FlakyDeviceVerifier(DeviceRoutedVerifier):
    """Device tier that fails N probes then answers — the shape of a
    transient accelerator outage."""

    name = "flaky-test"

    def __init__(self, fail_times: int = 1, device_min_sigs: int = 4):
        super().__init__(device_min_sigs=device_min_sigs)
        self.fail_times = fail_times
        self.device_calls = 0

    def _verify_ed25519_device(self, jobs):
        self.device_calls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise RuntimeError("device down (test)")
        return np.zeros(len(jobs), dtype=bool)


def _jobs(n):
    return [VerifyJob(bytes(32), bytes(32), bytes(64))] * n


def test_degrade_device_gates_then_reprobes_back():
    v = FlakyDeviceVerifier(fail_times=1, device_min_sigs=4)
    # Cooldown long enough that the gate-closed routing check below runs
    # before the first re-probe, short enough to watch recovery.
    assert degrade_device(v, cooldown_s=0.25) is True
    assert v.degraded == 1
    assert v.device_gate is not None and not v.device_gate.is_set()
    # Gate closed: a batch above the size crossover still host-routes.
    v.verify_batch(_jobs(8))
    assert v.host_batches == 1 and v.device_calls == 0
    # The re-probe thread eats the one remaining failure, then the next
    # probe answers and re-opens the gate.
    deadline = time.monotonic() + 5.0
    while not v.device_gate.is_set() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert v.device_gate.is_set(), "re-probe never re-opened the gate"
    assert v.reprobes_failed == 1
    assert v.reprobes_ok == 1
    # Device tier trusted again: big batches dispatch to the device.
    before = v.device_calls
    v.verify_batch(_jobs(8))
    assert v.device_calls == before + 1


def test_degrade_device_noop_without_device_tier():
    assert degrade_device(CpuVerifier(), cooldown_s=0.01) is False


def test_degrade_device_repeat_only_bumps_counter():
    v = FlakyDeviceVerifier(fail_times=10_000, device_min_sigs=4)
    assert degrade_device(v, cooldown_s=30.0) is True
    first_thread = v._reprobe_thread
    assert degrade_device(v, cooldown_s=30.0) is True
    assert v.degraded == 2
    assert v._reprobe_thread is first_thread, "second re-probe thread spawned"


def test_smm_degrade_and_reverify_delivers_on_host():
    """The drain-side seam: a batch whose device verify RAISED must be
    re-verified on the host tier and DELIVERED (not rejected), with the
    verifier demoted behind the gate."""
    from corda_tpu.crypto.async_verify import VerifyBatchHandle
    from corda_tpu.node.statemachine import StateMachineManager

    class _Svc:
        verifier = FlakyDeviceVerifier(fail_times=10_000, device_min_sigs=4)

    smm = object.__new__(StateMachineManager)
    smm.async_verify = _Svc()
    smm.metrics = {"verify_device_degraded": 0}
    delivered = []
    smm._deliver_verify_results = lambda ctx, ok: delivered.append((ctx, ok))

    handle = VerifyBatchHandle(_jobs(6), context="ctx")
    handle.error = RuntimeError("device blew up")
    assert smm._degrade_and_reverify(handle) is True
    assert smm.metrics["verify_device_degraded"] == 1
    assert _Svc.verifier.degraded == 1
    (ctx, ok), = delivered
    assert ctx == "ctx" and len(ok) == 6 and not ok.any()  # garbage sigs


def test_smm_degrade_falls_back_for_host_only_verifier():
    from corda_tpu.crypto.async_verify import VerifyBatchHandle
    from corda_tpu.node.statemachine import StateMachineManager

    class _Svc:
        verifier = CpuVerifier()

    smm = object.__new__(StateMachineManager)
    smm.async_verify = _Svc()
    smm.metrics = {"verify_device_degraded": 0}
    handle = VerifyBatchHandle(_jobs(2), context="ctx")
    handle.error = RuntimeError("host oracle bug")
    assert smm._degrade_and_reverify(handle) is False
    assert smm.metrics["verify_device_degraded"] == 0


# ---------------------------------------------------------------------------
# Cluster soaks (real TCP + sqlite raft cluster; slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_leader_kill_exactly_once_with_measured_recovery(tmp_path):
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    result = run_chaos_loadtest(
        n_tx=60, kill_leader=True, rate_tx_s=80.0,
        base_dir=str(tmp_path), max_seconds=120.0)
    assert any("killed leader" in d for d in result.disruptions), \
        result.disruptions
    assert result.exactly_once, result.to_json()
    assert result.cluster_committed == 60
    assert result.leader_kill_recovery_s is not None
    assert result.leader_kill_recovery_s < 60.0


@pytest.mark.slow
def test_pipelined_leader_kill_mid_overlap_exactly_once(tmp_path):
    """Round 18 chaos leg: with the pipelined commit plane on (the
    default), stall every fsync so sealed-but-uncommitted rounds pile up
    behind the replicating one, then kill the leader mid-burst — the
    kill lands while rounds N and N+1 genuinely overlap. Redelivered
    replies after the crash must stay idempotent: exactly once, nothing
    lost, nothing doubled."""
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    plan = faults.FaultPlan(7, [
        faults.FaultRule("raft.fsync", "stall", delay_s=0.02)])
    result = run_chaos_loadtest(
        plan=plan, n_tx=60, kill_leader=True, rate_tx_s=200.0,
        base_dir=str(tmp_path), max_seconds=120.0)
    assert any("killed leader" in d for d in result.disruptions), \
        result.disruptions
    assert result.faults_injected.get("raft.fsync:stall", 0) > 0
    assert result.exactly_once, result.to_json()
    assert result.cluster_committed == 60
    assert result.leader_kill_recovery_s is not None


@pytest.mark.slow
def test_lossy_transport_redelivers_to_completion(tmp_path):
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    result = run_chaos_loadtest(
        plan="lossy", n_tx=60, rate_tx_s=80.0,
        base_dir=str(tmp_path), max_seconds=120.0)
    assert result.exactly_once, result.to_json()
    assert result.faults_injected.get("transport.send:drop", 0) > 0, \
        "lossy plan never dropped a frame"


@pytest.mark.slow
def test_slow_disk_plan_completes(tmp_path):
    """Every raft log append stalls (group commit coalesces 30 tx into a
    handful of fsyncs, so p=1.0 is what actually exercises the point) —
    the cluster must still commit everything exactly once."""
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    plan = faults.FaultPlan(5, [
        faults.FaultRule("raft.fsync", "stall", delay_s=0.02)])
    result = run_chaos_loadtest(
        plan=plan, n_tx=30, base_dir=str(tmp_path), max_seconds=120.0)
    assert result.exactly_once, result.to_json()
    assert result.faults_injected.get("raft.fsync:stall", 0) > 0
