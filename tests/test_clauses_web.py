"""Clause framework, node web API, and cluster service identities.

Mirrors the reference's clause tests (reference: core/src/test/kotlin/net/
corda/core/contracts/clauses/*), the web servlets (node/.../servlets/
DataUploadServlet.kt, AttachmentDownloadServlet.kt) and
ServiceIdentityGenerator (node/.../utilities/ServiceIdentityGenerator.kt).
"""

import json
import urllib.request

import pytest

from corda_tpu.contracts.clauses import (
    AllComposition,
    AnyComposition,
    Clause,
    FirstComposition,
    GroupClauseVerifier,
    verify_clause,
)
from corda_tpu.contracts.dsl import RequirementFailed, require_that
from corda_tpu.contracts.structures import AuthenticatedObject


class _Cmd:
    pass


class _CmdA(_Cmd):
    pass


class _CmdB(_Cmd):
    pass


def auth(cmd):
    return AuthenticatedObject((), (), cmd)


class RecordingClause(Clause):
    def __init__(self, name, required=(), fail=False):
        self.name = name
        self.required_commands = required
        self.fail = fail
        self.ran = 0

    def verify(self, tx, inputs, outputs, commands, key):
        self.ran += 1
        with require_that() as req:
            req(f"clause {self.name}", not self.fail)
        return {c.value for c in self.get_matched_commands(commands)}


class TestClauses:
    def test_first_composition_dispatches_on_command(self):
        issue = RecordingClause("issue", (_CmdA,))
        move = RecordingClause("move", (_CmdB,))
        cmds = [auth(_CmdB())]
        verify_clause(None, FirstComposition(issue, move), cmds)
        assert (issue.ran, move.ran) == (0, 1)

    def test_all_composition_runs_every_match(self):
        a = RecordingClause("a", (_CmdA,))
        b = RecordingClause("b", (_CmdA,))
        verify_clause(None, AllComposition(a, b), [auth(_CmdA())])
        assert (a.ran, b.ran) == (1, 1)

    def test_any_composition_requires_a_match(self):
        a = RecordingClause("a", (_CmdA,))
        with pytest.raises(RequirementFailed, match="no clause matched"):
            verify_clause(None, AnyComposition(a), [auth(_CmdB())])

    def test_failing_clause_propagates(self):
        bad = RecordingClause("bad", (_CmdA,), fail=True)
        with pytest.raises(RequirementFailed, match="clause bad"):
            verify_clause(None, FirstComposition(bad), [auth(_CmdA())])

    def test_unprocessed_declared_command_rejected(self):
        class Lazy(Clause):
            required_commands = (_CmdA,)

            def verify(self, tx, inputs, outputs, commands, key):
                return set()  # pretends to match but processes nothing

        with pytest.raises(RequirementFailed, match="not processed"):
            verify_clause(None, Lazy(), [auth(_CmdA())])

    def test_group_clause_verifier_fans_groups(self):
        class FakeGroup:
            def __init__(self, key):
                self.inputs, self.outputs, self.grouping_key = (), (), key

        seen = []

        class PerGroup(Clause):
            required_commands = (_CmdA,)

            def verify(self, tx, inputs, outputs, commands, key):
                seen.append(key)
                return {c.value for c in self.get_matched_commands(commands)}

        class Verifier(GroupClauseVerifier):
            def group_states(self, tx):
                return [FakeGroup("g1"), FakeGroup("g2")]

        verify_clause(None, Verifier(PerGroup()), [auth(_CmdA())])
        assert seen == ["g1", "g2"]


class TestWebServer:
    def test_status_metrics_and_attachment_roundtrip(self, tmp_path):
        from corda_tpu.node.config import NodeConfig
        from corda_tpu.node.node import Node

        node = Node(NodeConfig(
            name="WebNode", base_dir=tmp_path / "WebNode",
            network_map=tmp_path / "netmap.json", web_port=0)).start()
        base = f"http://127.0.0.1:{node.webserver.port}"
        try:
            status = json.load(urllib.request.urlopen(f"{base}/api/status"))
            assert status["name"] == "WebNode"
            metrics = json.load(urllib.request.urlopen(f"{base}/api/metrics"))
            assert "started" in metrics

            blob = b"legal prose attachment" * 50
            req = urllib.request.Request(
                f"{base}/upload/attachment", data=blob, method="POST")
            uploaded = json.load(urllib.request.urlopen(req))
            att_id = uploaded["id"]
            back = urllib.request.urlopen(
                f"{base}/attachments/{att_id}").read()
            assert back == blob

            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/attachments/{'0' * 64}")
        finally:
            node.stop()


class TestServiceIdentity:
    def test_any_cluster_member_signature_validates(self):
        from corda_tpu.crypto.keys import KeyPair
        from corda_tpu.utils.service_identity import generate_service_identity

        members = [KeyPair.generate(bytes([0x81 + i]) * 32) for i in range(3)]
        cluster = generate_service_identity(
            "Raft Notary Service", [m.public for m in members])
        for member in members:
            sig = member.sign(b"notarised-tx-id")
            # 1-of-n composite: each member key fulfils the service identity.
            assert cluster.owning_key.is_fulfilled_by({sig.by})
        outsider = KeyPair.generate(b"\x99" * 32)
        assert not cluster.owning_key.is_fulfilled_by({outsider.public})


class TestMonitoringBridge:
    def test_flow_timings_and_metrics_history(self, tmp_path):
        """Per-flow completion timings + the counters time-series ring —
        the JMX/Jolokia monitoring capability (reference: Node.kt:313,163)
        re-based on /api/metrics + /api/metrics/history."""
        import corda_tpu.tools.demo_cordapp  # noqa: F401
        from corda_tpu.node.config import NodeConfig
        from corda_tpu.node.node import Node
        from corda_tpu.flows.api import flow_registry

        node = Node(NodeConfig(
            name="MonNode", base_dir=tmp_path / "MonNode",
            network_map=tmp_path / "netmap.json", notary="simple",
            web_port=0)).start()
        try:
            logic = flow_registry.create("IssueAndNotariseFlow", (3,))
            handle = node.smm.add(logic)
            for _ in range(2000):
                node.run_once(timeout=0.001)
                if handle.result.done:
                    break
            assert handle.result.done and handle.result.exception() is None

            timings = node.smm.flow_timings
            assert timings["IssueAndNotariseFlow"]["count"] == 1
            assert timings["IssueAndNotariseFlow"]["max_ms"] > 0
            # NotaryClientFlow ran as a sub-flow of the same state machine,
            # so only the top-level flow completes a run.

            base = f"http://127.0.0.1:{node.webserver.port}"
            metrics = json.load(urllib.request.urlopen(f"{base}/api/metrics"))
            assert metrics["flow_timings"]["IssueAndNotariseFlow"]["count"] == 1

            # Force two history samples through the run loop's cadence gate.
            node._metrics_sampled_at = 0.0
            node.run_once(timeout=0.001)
            node._metrics_sampled_at = 0.0
            node.run_once(timeout=0.001)
            history = json.load(
                urllib.request.urlopen(f"{base}/api/metrics/history"))
            assert len(history) >= 2
            # Round 16: the history endpoint serves newest-first.
            assert history[0]["ts"] >= history[-1]["ts"]
            assert "verify_sigs" in history[-1]
        finally:
            node.stop()
