"""CommercialPaper rules via the ledger DSL + DvP trade of paper.

Mirrors the reference's CommercialPaperTests (reference: finance/src/test/
kotlin/net/corda/contracts/CommercialPaperTests.kt) written in the test DSL
(test-utils/.../TestDSL.kt), plus the trader-demo shape (SellerFlow/BuyerFlow
wrapping TwoPartyTradeFlow over CommercialPaper).
"""

import pytest

from corda_tpu.contracts.structures import Issued, Timestamp, now_micros
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.finance import Amount, CashState
from corda_tpu.finance.cash import CashIssue, CashMove
from corda_tpu.finance.commercial_paper import (
    CommercialPaper,
    CPIssue,
    CPMove,
    CPRedeem,
    CPState,
)
from corda_tpu.testing.ledger_dsl import DslError, ledger

MEGA_KEY = KeyPair.generate(b"\x41" * 32)
MEGA = Party.of("MegaCorp", MEGA_KEY.public)
ALICE_KEY = KeyPair.generate(b"\x42" * 32)
ALICE = Party.of("Alice", ALICE_KEY.public)
NOTARY = Party.of("Notary", KeyPair.generate(b"\x43" * 32).public)

USD = "USD"
NOW = now_micros()
WEEK = 7 * 24 * 3600 * 1_000_000


def issued_usd(qty):
    return Amount(qty, Issued(MEGA.ref(b"\x01"), USD))


def paper(owner=None, maturity=None):
    return CPState(MEGA.ref(b"\x01"), owner or MEGA.owning_key,
                   issued_usd(1000), maturity or NOW + WEEK)


class TestCommercialPaperRules:
    def test_issue_move_redeem_lifecycle(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output("paper", paper())
            tx.command(CPIssue(), MEGA.owning_key)
            tx.timestamp(Timestamp.around(NOW, 1000))
            tx.verifies()
        with l.transaction() as tx:
            tx.input("paper")
            tx.output("alice's paper", paper(owner=ALICE.owning_key))
            tx.command(CPMove(), MEGA.owning_key)
            tx.verifies()
        with l.transaction() as tx:  # redeem at maturity for cash
            tx.input("alice's paper")
            tx.output(CashState(issued_usd(1000), ALICE.owning_key))
            tx.input(CashState(issued_usd(1000), MEGA.owning_key))
            tx.command(CPRedeem(), ALICE.owning_key)
            tx.command(CashMove(), MEGA.owning_key)
            tx.timestamp(Timestamp.around(NOW + WEEK, 1000))
            tx.verifies()

    def test_issue_requires_issuer_signature(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output(paper())
            tx.command(CPIssue(), ALICE.owning_key)  # not the issuer
            tx.timestamp(Timestamp.around(NOW, 1000))
            tx.fails_with("signed by the issuer")

    def test_issue_requires_future_maturity(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output(paper(maturity=NOW - WEEK))
            tx.command(CPIssue(), MEGA.owning_key)
            tx.timestamp(Timestamp.around(NOW, 1000))
            tx.fails_with("maturity date is in the future")

    def test_cannot_redeem_before_maturity_with_tweak(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output("paper", paper(owner=ALICE.owning_key))
            tx.command(CPIssue(), MEGA.owning_key)
            tx.timestamp(Timestamp.around(NOW, 1000))
            tx.verifies()
        with l.transaction() as tx:
            tx.input("paper")
            tx.output(CashState(issued_usd(1000), ALICE.owning_key))
            tx.input(CashState(issued_usd(1000), MEGA.owning_key))
            tx.command(CPRedeem(), ALICE.owning_key)
            tx.command(CashMove(), MEGA.owning_key)
            with tx.tweak() as tw:  # too early
                tw.timestamp(Timestamp.around(NOW, 1000))
                tw.fails_with("must have matured")
            tx.timestamp(Timestamp.around(NOW + WEEK, 1000))
            tx.verifies()

    def test_redeem_must_pay_face_value(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(paper(owner=ALICE.owning_key))
            tx.output(CashState(issued_usd(600), ALICE.owning_key))  # short
            tx.output(CashState(issued_usd(400), MEGA.owning_key))
            tx.input(CashState(issued_usd(1000), MEGA.owning_key))
            tx.command(CPRedeem(), ALICE.owning_key)
            tx.command(CashMove(), MEGA.owning_key)
            tx.timestamp(Timestamp.around(NOW + WEEK, 1000))
            tx.fails_with("face value")

    def test_move_cannot_change_terms(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(paper())
            bigger = CPState(MEGA.ref(b"\x01"), ALICE.owning_key,
                             issued_usd(2000), NOW + WEEK)
            tx.output(bigger)
            tx.command(CPMove(), MEGA.owning_key)
            # Different face value = a different group with no inputs and no
            # issue command -> rejected.
            tx.fails_with("CPRedeem")

    def test_dsl_requires_verification_call(self):
        l = ledger(NOTARY)
        with pytest.raises(DslError, match="without verifies"):
            with l.transaction() as tx:
                tx.output(paper())


class TestPaperTrade:
    def test_dvp_trade_of_commercial_paper(self):
        """trader-demo shape: seller holds paper, buyer pays cash — one
        atomic swap through the validating notary."""
        from corda_tpu.finance import Cash
        from corda_tpu.finance.trade import BuyerFlow, SellerFlow
        from corda_tpu.testing.mock_network import MockNetwork

        net = MockNetwork()
        try:
            notary = net.create_notary_node("Notary", validating=True)
            seller = net.create_node("Seller")
            buyer = net.create_node("Buyer")

            # Seller self-issues paper (it is its own issuer here). The
            # timestamped issuance needs the notary's signature — obtain it
            # through the notarisation flow before the paper can be traded.
            from corda_tpu.flows.notary import NotaryClientFlow

            issue = CommercialPaper.generate_issue(
                seller.identity.ref(b"\x01"), Amount(
                    900, Issued(seller.identity.ref(b"\x01"), USD)),
                now_micros() + WEEK, notary.identity)
            issue.set_time(Timestamp.around(now_micros(), 30_000_000))
            issue.sign_with(seller.key)
            issue_stx = issue.to_signed_transaction(
                check_sufficient_signatures=False)
            h = seller.start_flow(NotaryClientFlow(issue_stx))
            net.run_network()
            issue_stx = issue_stx.with_additional_signature(h.result.result())
            seller.record_transaction(issue_stx)

            cash_issue = Cash.generate_issue(
                Amount(1_000, USD), buyer.identity.ref(b"\x02"),
                buyer.identity.owning_key, notary.identity)
            cash_issue.sign_with(buyer.key)
            cash_stx = cash_issue.to_signed_transaction()
            buyer.record_transaction(cash_stx)

            buyer.register_initiated_flow(
                "SellerFlow",
                lambda party: BuyerFlow(party, Amount(800, USD),
                                        notary.identity))
            handle = seller.start_flow(SellerFlow(
                buyer.identity, issue_stx.tx.out_ref(0), Amount(750, USD)))
            net.run_network()
            final = handle.result.result()
            papers = [o.data for o in final.tx.outputs
                      if isinstance(o.data, CPState)]
            assert [p.owner for p in papers] == [buyer.identity.owning_key]
        finally:
            net.stop_nodes()


def test_two_identical_papers_cannot_share_one_payment():
    """Regression: N identical papers in one group must each claim their own
    cash — a single face-value payment cannot extinguish both."""
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(paper(owner=ALICE.owning_key))
        tx.input(paper(owner=ALICE.owning_key))  # identical twin
        tx.output(CashState(issued_usd(1000), ALICE.owning_key))  # only ONE
        tx.input(CashState(issued_usd(1000), MEGA.owning_key))
        tx.command(CPRedeem(), ALICE.owning_key)
        tx.command(CashMove(), MEGA.owning_key)
        tx.timestamp(Timestamp.around(NOW + WEEK, 1000))
        tx.fails_with("face value")
    with l.transaction() as tx:  # paying for both is fine
        tx.input(paper(owner=ALICE.owning_key))
        tx.input(paper(owner=ALICE.owning_key))
        tx.output(CashState(issued_usd(2000), ALICE.owning_key))
        tx.input(CashState(issued_usd(2000), MEGA.owning_key))
        tx.command(CPRedeem(), ALICE.owning_key)
        tx.command(CashMove(), MEGA.owning_key)
        tx.timestamp(Timestamp.around(NOW + WEEK, 1000))
        tx.verifies()
