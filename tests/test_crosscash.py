"""CrossCash convergence checking over real OS-process nodes.

The reference's CrossCashTest predicts per-node balances under concurrent
random traffic and polls the cluster until it converges (reference:
tools/loadtest/.../tests/CrossCashTest.kt:1-80, LoadTest.kt:121-129);
Disruption.kt:18-60 adds kill/hang/CPU-strain fault injection. These tests
run the whole loop: seeded traffic, prediction, gather, convergence — and
prove the checker actually detects an injected lost-update divergence.
"""

import pytest

from corda_tpu.tools.crosscash import (
    CrossCashCommand,
    CrossCashModel,
    generate_wave,
    run_crosscash,
    vaults_match,
)


def test_model_and_matcher_unit():
    m = CrossCashModel()
    m.apply(CrossCashCommand("issue", "A", 500, "B", 1))
    m.apply(CrossCashCommand("pay", "B", 200, "C"))
    assert m.balances == {"B": 300, "C": 200}
    assert vaults_match({"B": 300, "C": 200},
                        {"B": {"A": 300}, "C": {"A": 200}})
    assert not vaults_match({"B": 300}, {"B": {"A": 299}})   # lost update
    assert not vaults_match({"B": 300}, {"B": {"A": 600}})   # double spend
    assert vaults_match({"B": 0}, {})                        # absent == zero


def test_generate_wave_respects_balances():
    import random

    m = CrossCashModel()
    rng = random.Random(3)
    names = ["A", "B", "C"]
    for _ in range(50):
        for cmd in generate_wave(m, names, rng, 2):
            if cmd.kind == "pay":
                assert m.balances.get(cmd.node, 0) >= cmd.quantity
                assert cmd.recipient != cmd.node
            m.apply(cmd)


@pytest.mark.slow
def test_crosscash_converges_simple_notary(tmp_path):
    r = run_crosscash(n_waves=3, wave_size=2, clients=2, notary="simple",
                      seed=11, base_dir=str(tmp_path))
    assert r.commands_committed > 0
    assert r.converged, (r.expected, r.gathered)


@pytest.mark.slow
def test_crosscash_detects_injected_lost_update(tmp_path):
    # The fault-injection hook drops one committed pay from the model: the
    # cluster is fine but the PREDICTION diverges — exactly the shape a
    # real double-spend/lost-update would produce on the other side. The
    # checker MUST refuse to converge.
    r = run_crosscash(n_waves=3, wave_size=2, clients=2, notary="simple",
                      seed=11, base_dir=str(tmp_path),
                      converge_timeout=8.0, _drop_model_update=True)
    assert not r.converged


@pytest.mark.slow
def test_crosscash_converges_under_kill_sigstop_strain(tmp_path):
    # The reference's full disruption inventory in one seeded run against a
    # 3-member Raft cluster: SIGKILL+restart, SIGSTOP hang, and CPU strain
    # (SIGSTOP duty-cycling), one per successive wave. Every committed
    # command must still land exactly once in every vault.
    r = run_crosscash(
        n_waves=5, wave_size=3, clients=3, notary="raft",
        seed=23, base_dir=str(tmp_path),
        disrupt=("kill-follower", "sigstop-follower", "strain-follower"),
        disrupt_wave=1, max_seconds=480.0)
    assert len(r.disruptions) >= 4  # kill+restart, stop+cont, strain
    assert any("SIGKILL" in x for x in r.disruptions)
    assert any("strain" in x for x in r.disruptions)
    assert r.commands_committed > 0
    assert r.converged, (r.disruptions, r.expected, r.gathered)
