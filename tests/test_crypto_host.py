"""Host crypto layer: keys, composite keys, Merkle/partial-Merkle, SignedData.

Mirrors the reference's CompositeKeyTests and PartialMerkleTreeTest coverage
(reference: core/src/test/kotlin/net/corda/core/crypto/CompositeKeyTests.kt,
PartialMerkleTreeTest.kt) against the new implementations.
"""

import pytest

from corda_tpu.crypto import (
    CompositeKey,
    DigitalSignature,
    KeyPair,
    MerkleTree,
    MerkleTreeException,
    PartialMerkleTree,
    Party,
    SecureHash,
    SignatureError,
    SignedData,
)
from corda_tpu.serialization.codec import serialize, deserialize


def kp(i: int) -> KeyPair:
    return KeyPair.generate(bytes([i]) * 32)


ALICE, BOB, CHARLIE = kp(1), kp(2), kp(3)


class TestKeys:
    def test_sign_verify_roundtrip(self):
        sig = ALICE.sign(b"hello")
        sig.verify(b"hello")
        assert sig.is_valid(b"hello")
        assert not sig.is_valid(b"goodbye")

    def test_bad_signature_raises(self):
        sig = ALICE.sign(b"hello")
        with pytest.raises(SignatureError):
            sig.verify(b"other")

    def test_sign_as_party(self):
        party = Party.of("Alice Corp", ALICE.public)
        sig = ALICE.sign_as(b"data", party)
        assert sig.signer == party
        sig.verify(b"data")

    def test_sign_as_wrong_party_rejected(self):
        party = Party.of("Bob Inc", BOB.public)
        with pytest.raises(ValueError):
            ALICE.sign_as(b"data", party)


class TestCompositeKey:
    def test_leaf_fulfilment(self):
        leaf = ALICE.public.composite
        assert leaf.is_fulfilled_by(ALICE.public)
        assert not leaf.is_fulfilled_by(BOB.public)

    def test_and_requirement(self):
        both = CompositeKey.Builder().add_keys(ALICE.public, BOB.public).build()
        assert both.threshold == 2
        assert not both.is_fulfilled_by(ALICE.public)
        assert both.is_fulfilled_by({ALICE.public, BOB.public})

    def test_or_requirement(self):
        either = CompositeKey.Builder().add_keys(ALICE.public, BOB.public).build(threshold=1)
        assert either.is_fulfilled_by(ALICE.public)
        assert either.is_fulfilled_by(BOB.public)
        assert not either.is_fulfilled_by(CHARLIE.public)

    def test_weighted_threshold(self):
        # CEO weight 2, two assistants weight 1 each, threshold 2:
        # CEO alone passes; one assistant fails; both assistants pass.
        key = (
            CompositeKey.Builder()
            .add_key(ALICE.public, weight=2)
            .add_key(BOB.public, weight=1)
            .add_key(CHARLIE.public, weight=1)
            .build(threshold=2)
        )
        assert key.is_fulfilled_by(ALICE.public)
        assert not key.is_fulfilled_by(BOB.public)
        assert key.is_fulfilled_by({BOB.public, CHARLIE.public})

    def test_nested_tree(self):
        inner = CompositeKey.Builder().add_keys(BOB.public, CHARLIE.public).build(threshold=1)
        outer = CompositeKey.Builder().add_key(ALICE.public.composite).add_key(inner).build()
        assert not outer.is_fulfilled_by(ALICE.public)
        assert outer.is_fulfilled_by({ALICE.public, CHARLIE.public})
        assert outer.keys == {ALICE.public, BOB.public, CHARLIE.public}

    def test_contains_any_and_single(self):
        leaf = ALICE.public.composite
        assert leaf.single_key == ALICE.public
        tree = CompositeKey.Builder().add_keys(ALICE.public, BOB.public).build()
        assert tree.contains_any([BOB.public])
        assert not tree.contains_any([CHARLIE.public])
        with pytest.raises(ValueError):
            _ = tree.single_key

    def test_degenerate_nodes_rejected(self):
        from corda_tpu.crypto import CompositeKeyNode

        with pytest.raises(ValueError):
            CompositeKey.Builder().build()  # no children
        with pytest.raises(ValueError):
            CompositeKeyNode(0, (ALICE.public.composite,), (1,))  # threshold 0
        with pytest.raises(ValueError):
            CompositeKeyNode(1, (ALICE.public.composite,), (0,))  # weight 0
        with pytest.raises(ValueError):
            CompositeKeyNode(1, (ALICE.public.composite,), (-1, 1))  # mismatch

    def test_base58_roundtrip(self):
        tree = CompositeKey.Builder().add_keys(ALICE.public, BOB.public).build(threshold=1)
        assert CompositeKey.parse_from_base58(tree.to_base58_string()) == tree

    def test_serialization_roundtrip(self):
        tree = (
            CompositeKey.Builder()
            .add_key(ALICE.public, weight=3)
            .add_key(BOB.public.composite)
            .build(threshold=2)
        )
        assert deserialize(serialize(tree).bytes) == tree


def leaves(n: int) -> list[SecureHash]:
    return [SecureHash.sha256(bytes([i])) for i in range(n)]


class TestMerkle:
    def test_empty_rejected(self):
        with pytest.raises(MerkleTreeException):
            MerkleTree.build([])

    def test_single_leaf_root(self):
        (h,) = leaves(1)
        assert MerkleTree.build([h]).hash == h

    def test_two_leaves(self):
        a, b = leaves(2)
        assert MerkleTree.build([a, b]).hash == a.hash_concat(b)

    def test_odd_level_duplicates_last(self):
        a, b, c = leaves(3)
        expect = a.hash_concat(b).hash_concat(c.hash_concat(c))
        assert MerkleTree.build([a, b, c]).hash == expect

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 6, 7, 8, 9, 16, 31])
    def test_partial_proofs_verify(self, n):
        hs = leaves(n)
        tree = MerkleTree.build(hs)
        # Prove every single leaf and one multi-leaf subset.
        for h in hs:
            pmt = PartialMerkleTree.build(tree, [h])
            assert pmt.verify(tree.hash, [h])
        subset = hs[:: max(1, n // 3)]
        pmt = PartialMerkleTree.build(tree, subset)
        assert pmt.verify(tree.hash, subset)

    def test_partial_proof_wrong_root_fails(self):
        hs = leaves(5)
        tree = MerkleTree.build(hs)
        pmt = PartialMerkleTree.build(tree, [hs[2]])
        assert not pmt.verify(SecureHash.zero(), [hs[2]])

    def test_partial_proof_wrong_leaves_fails(self):
        hs = leaves(5)
        tree = MerkleTree.build(hs)
        pmt = PartialMerkleTree.build(tree, [hs[2]])
        assert not pmt.verify(tree.hash, [hs[3]])
        assert not pmt.verify(tree.hash, [hs[2], hs[3]])

    def test_unknown_hash_rejected_at_build(self):
        hs = leaves(4)
        tree = MerkleTree.build(hs)
        with pytest.raises(MerkleTreeException):
            PartialMerkleTree.build(tree, [SecureHash.sha256(b"not-in-tree")])

    def test_duplicate_leaf_not_provable_as_real(self):
        # With 3 leaves the 4th position is a duplicate of leaf 3; proving
        # leaf 3 must still work and use the duplicate as a bare hash.
        hs = leaves(3)
        tree = MerkleTree.build(hs)
        pmt = PartialMerkleTree.build(tree, [hs[2]])
        assert pmt.verify(tree.hash, [hs[2]])
        assert pmt.included_hashes() == [hs[2]]

    def test_partial_tree_serialization_roundtrip(self):
        hs = leaves(7)
        tree = MerkleTree.build(hs)
        pmt = PartialMerkleTree.build(tree, [hs[1], hs[4]])
        restored = deserialize(serialize(pmt).bytes)
        assert restored.verify(tree.hash, [hs[1], hs[4]])


class TestSignedData:
    def test_verified_returns_payload(self):
        raw = serialize("the payload")
        signed = SignedData(raw=raw, sig=ALICE.sign(raw.bytes))
        assert signed.verified() == "the payload"

    def test_tampered_payload_rejected(self):
        raw = serialize("the payload")
        sig = ALICE.sign(raw.bytes)
        tampered = SignedData(raw=serialize("evil payload"), sig=sig)
        with pytest.raises(SignatureError):
            tampered.verified()


class TestFastEd25519Conformance:
    """fast_ed25519 (OpenSSL accept / oracle-authoritative reject) must be
    bit-identical to the ref_ed25519 oracle — including the S >= L accept
    corner OpenSSL itself rejects."""

    def test_sign_and_public_key_bit_identical(self):
        import random

        from corda_tpu.crypto import fast_ed25519 as fast
        from corda_tpu.crypto import ref_ed25519 as ref

        rng = random.Random(11)
        for _ in range(8):
            seed = bytes(rng.randrange(256) for _ in range(32))
            msg = bytes(rng.randrange(256) for _ in range(rng.choice([0, 32])))
            assert fast.sign(seed, msg) == ref.sign(seed, msg)
            assert fast.public_key(seed) == ref.public_key(seed)

    def test_verify_matches_oracle_on_adversarial_corpus(self):
        import random

        from corda_tpu.crypto import fast_ed25519 as fast
        from corda_tpu.crypto import ref_ed25519 as ref

        rng = random.Random(12)
        seed = bytes(rng.randrange(256) for _ in range(32))
        pk = ref.public_key(seed)
        msg = bytes(rng.randrange(256) for _ in range(32))
        sig = ref.sign(seed, msg)
        s_plus_l = int.from_bytes(sig[32:], "little") + ref.L
        flipped = bytearray(sig)
        flipped[7] ^= 1
        cases = [
            (pk, msg, sig),                    # valid
            (pk, msg, bytes(flipped)),         # corrupt
            (pk, b"x" * 32, sig),              # wrong message
            (pk, msg, sig[:32] + s_plus_l.to_bytes(32, "little")),  # S+L
            (b"\x00" * 32, msg, b"\x00" * 64),  # degenerate
            (b"\xff" * 32, msg, sig),          # invalid point
            (pk, msg, sig[:40]),               # short sig
            (pk[:16], msg, sig),               # short key
        ]
        # non-canonical A encodings (y >= p) that still decompress
        for yy in range(19):
            enc = (yy + ref.P).to_bytes(32, "little")
            if ref.decompress(enc) is not None:
                cases.append((enc, msg, sig))
        for pk_c, msg_c, sig_c in cases:
            assert fast.verify(pk_c, msg_c, sig_c) == ref.verify(
                pk_c, msg_c, sig_c)

    def test_s_plus_l_accepted_via_fallback(self):
        # The one known OpenSSL/oracle divergence: the fallback must accept.
        from corda_tpu.crypto import fast_ed25519 as fast
        from corda_tpu.crypto import ref_ed25519 as ref

        seed = b"\x21" * 32
        pk = ref.public_key(seed)
        msg = b"m" * 32
        sig = ref.sign(seed, msg)
        s = int.from_bytes(sig[32:], "little") + ref.L
        mangled = sig[:32] + s.to_bytes(32, "little")
        assert fast.verify(pk, msg, mangled) is True


def test_clean_venv_install_smoke(tmp_path):
    # Round-3 VERDICT item 5: `pip install .` into a fresh venv must yield
    # a working package with the OpenSSL fast path ACTIVE (cryptography is
    # now a declared dependency; --system-site-packages + --no-deps keeps
    # this offline-friendly while still exercising packaging metadata).
    import subprocess
    import sys

    # The probe asserts the OpenSSL fast path is ACTIVE, which needs the
    # wheel; and pip refuses the install below requires-python (>=3.11).
    # On a container missing either, this is an environment gap, not a
    # packaging regression — skip with the reason instead of failing.
    pytest.importorskip(
        "cryptography",
        reason="the 'cryptography' wheel is not installed — the install "
               "probe asserts the OpenSSL fast path is active")
    if sys.version_info < (3, 11):
        pytest.skip("interpreter is %d.%d but pyproject requires-python is "
                    ">=3.11 — pip rejects the install before packaging is "
                    "exercised" % sys.version_info[:2])

    import os
    import sysconfig

    venv_dir = tmp_path / "venv"
    subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages",
         str(venv_dir)], check=True)
    py = venv_dir / "bin" / "python"
    # This test process may itself run inside a venv whose site-packages a
    # NESTED venv does not inherit; surface the parent's purelib (where
    # setuptools/jax/cryptography live) explicitly so the offline
    # --no-build-isolation build and the probe can import them.
    env = dict(os.environ,
               PYTHONPATH=sysconfig.get_paths()["purelib"])
    from pathlib import Path

    repo_root = str(Path(__file__).resolve().parents[1])
    subprocess.run(
        [str(py), "-m", "pip", "install", "--no-deps",
         "--no-build-isolation", "--quiet", repo_root],
        check=True, timeout=300, env=env)
    probe = (
        "from corda_tpu.crypto import fast_ed25519 as f\n"
        "assert f.available(), 'OpenSSL fast path inactive'\n"
        "pk = f.public_key(b'\\x01'*32)\n"
        "sig = f.sign(b'\\x01'*32, b'msg')\n"
        "assert f.verify(pk, b'msg', sig)\n"
        "import corda_tpu.node.node, corda_tpu.tools.loadtest\n"
        "print('install-ok')\n")
    out = subprocess.run([str(py), "-c", probe], capture_output=True,
                         text=True, check=True, cwd=str(tmp_path), env=env)
    assert "install-ok" in out.stdout


def test_jax_verifier_size_crossover_routing():
    """Batches under device_min_sigs take the host tier (the device round
    trip loses below ~512 sigs — measured crossover, provider.py
    DEVICE_MIN_SIGS_DEFAULT); at/above it they take the kernel. Both
    routes return identical verdicts and the counters attribute every
    batch."""
    from corda_tpu.crypto import ref_ed25519
    from corda_tpu.crypto.provider import JaxVerifier, VerifyJob

    jobs = []
    for i in range(8):
        seed = bytes([i + 1]) * 32
        msg = (b"m%d" % i).ljust(32, b".")
        sig = ref_ed25519.sign(seed, msg)
        if i == 5:
            sig = sig[:3] + bytes([sig[3] ^ 1]) + sig[4:]
        jobs.append(VerifyJob(ref_ed25519.public_key(seed), msg, sig))
    want = [i != 5 for i in range(8)]

    v = JaxVerifier(device_min_sigs=8)
    assert v.verify_batch(jobs[:3]).tolist() == want[:3]  # host route
    assert (v.host_batches, v.device_batches) == (1, 0)
    assert v.verify_batch(jobs).tolist() == want          # device route
    assert (v.host_batches, v.device_batches) == (1, 1)

    always_device = JaxVerifier(device_min_sigs=0)
    assert always_device.verify_batch(jobs[:3]).tolist() == want[:3]
    assert (always_device.host_batches, always_device.device_batches) == (0, 1)
