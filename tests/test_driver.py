"""Multi-process integration tier: real node processes, real sockets.

Mirrors the reference's DriverTests + demo smoke tests (reference:
node/src/integration-test/kotlin/net/corda/node/driver/DriverTests.kt,
samples/trader-demo/src/integration-test/.../TraderDemoTest.kt): nodes run as
separate OS processes spawned by the driver; the test drives them only
through RPC — exactly how an operator would.
"""

import time

import pytest

from corda_tpu.testing.driver import driver


@pytest.mark.slow
def test_two_processes_issue_and_notarise(tmp_path):
    with driver(tmp_path) as d:
        d.start_node("Notary", notary="simple",
                     cordapps=("corda_tpu.tools.demo_cordapp",))
        alice = d.start_node(
            "Alice", cordapps=("corda_tpu.tools.demo_cordapp",), rpc=True)
        client = alice.rpc("demo", "s3cret")
        try:
            # Wait until Alice's netmap refresh has seen the notary.
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                names = {n.legal_identity.name
                         for n in client.call("network_map_snapshot")}
                if "Notary" in names:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError("Alice never saw the notary")

            handle = client.start_flow("IssueAndNotariseFlow", 7)
            tx_id = client.wait_for_flow(handle, timeout=30.0)
            assert isinstance(tx_id, str) and len(tx_id) == 64
            # The notarised move is in Alice's storage and her vault holds
            # exactly the moved state.
            assert len(client.call("vault_snapshot")) == 1
        finally:
            client.close()


@pytest.mark.slow
def test_kill_notary_process_and_restart(tmp_path):
    """Process-level disruption (Disruption.kt 'kill' primitive): SIGKILL the
    notary mid-life, restart it from the same base_dir, and notarise again —
    the commit log and identity survive an actual process death."""
    with driver(tmp_path) as d:
        notary = d.start_node("Notary", notary="simple",
                     cordapps=("corda_tpu.tools.demo_cordapp",))
        alice = d.start_node(
            "Alice", cordapps=("corda_tpu.tools.demo_cordapp",), rpc=True)
        client = alice.rpc("demo", "s3cret")
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                names = {n.legal_identity.name
                         for n in client.call("network_map_snapshot")}
                if "Notary" in names:
                    break
                time.sleep(0.2)

            h1 = client.start_flow("IssueAndNotariseFlow", 1)
            client.wait_for_flow(h1, timeout=30.0)

            notary.kill()  # SIGKILL: no graceful shutdown whatsoever
            d.start_node("Notary", notary="simple",
                     cordapps=("corda_tpu.tools.demo_cordapp",))  # same base_dir

            h2 = client.start_flow("IssueAndNotariseFlow", 2)
            tx_id = client.wait_for_flow(h2, timeout=45.0)
            assert len(tx_id) == 64
        finally:
            client.close()
