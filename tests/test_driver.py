"""Multi-process integration tier: real node processes, real sockets.

Mirrors the reference's DriverTests + demo smoke tests (reference:
node/src/integration-test/kotlin/net/corda/node/driver/DriverTests.kt,
samples/trader-demo/src/integration-test/.../TraderDemoTest.kt): nodes run as
separate OS processes spawned by the driver; the test drives them only
through RPC — exactly how an operator would.
"""

import time

import pytest

from corda_tpu.testing.driver import driver


@pytest.mark.slow
def test_two_processes_issue_and_notarise(tmp_path):
    with driver(tmp_path) as d:
        d.start_node("Notary", notary="simple",
                     cordapps=("corda_tpu.tools.demo_cordapp",))
        alice = d.start_node(
            "Alice", cordapps=("corda_tpu.tools.demo_cordapp",), rpc=True)
        client = alice.rpc("demo", "s3cret")
        try:
            # Wait until Alice's netmap refresh has seen the notary.
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                names = {n.legal_identity.name
                         for n in client.call("network_map_snapshot")}
                if "Notary" in names:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError("Alice never saw the notary")

            handle = client.start_flow("IssueAndNotariseFlow", 7)
            tx_id = client.wait_for_flow(handle, timeout=30.0)
            assert isinstance(tx_id, str) and len(tx_id) == 64
            # The notarised move is in Alice's storage and her vault holds
            # exactly the moved state.
            assert len(client.call("vault_snapshot")) == 1
        finally:
            client.close()


@pytest.mark.slow
def test_kill_notary_process_and_restart(tmp_path):
    """Process-level disruption (Disruption.kt 'kill' primitive): SIGKILL the
    notary mid-life, restart it from the same base_dir, and notarise again —
    the commit log and identity survive an actual process death."""
    with driver(tmp_path) as d:
        notary = d.start_node("Notary", notary="simple",
                     cordapps=("corda_tpu.tools.demo_cordapp",))
        alice = d.start_node(
            "Alice", cordapps=("corda_tpu.tools.demo_cordapp",), rpc=True)
        client = alice.rpc("demo", "s3cret")
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                names = {n.legal_identity.name
                         for n in client.call("network_map_snapshot")}
                if "Notary" in names:
                    break
                time.sleep(0.2)

            h1 = client.start_flow("IssueAndNotariseFlow", 1)
            client.wait_for_flow(h1, timeout=30.0)

            notary.kill()  # SIGKILL: no graceful shutdown whatsoever
            d.start_node("Notary", notary="simple",
                     cordapps=("corda_tpu.tools.demo_cordapp",))  # same base_dir

            h2 = client.start_flow("IssueAndNotariseFlow", 2)
            tx_id = client.wait_for_flow(h2, timeout=45.0)
            assert len(tx_id) == 64
        finally:
            client.close()


def test_rendered_config_keeps_extra_toml_top_level(tmp_path):
    # Regression: extra_toml appended AFTER [[rpc_users]] made `verifier`
    # an rpc_users field — every RPC-enabled node silently ran the default
    # verifier. The rendered config must parse with the knob top-level.
    from corda_tpu.node.config import NodeConfig
    from corda_tpu.testing.driver import DEFAULT_RPC_USER, render_node_config

    text = render_node_config(
        name="N", node_dir=tmp_path, netmap=tmp_path / "netmap.json",
        cordapps=("corda_tpu.tools.loadgen",),
        extra_toml='verifier = "jax"\n[batch]\nmax_sigs = 4096\n'
                   "max_wait_ms = 2.0\n",
        rpc_users=[DEFAULT_RPC_USER])
    path = tmp_path / "node.toml"
    path.write_text(text)
    cfg = NodeConfig.load(str(path))
    assert cfg.verifier == "jax"
    assert cfg.batch.max_sigs == 4096
    assert cfg.rpc_users and cfg.rpc_users[0]["username"] == "demo"
    assert "verifier" not in cfg.rpc_users[0]


@pytest.mark.slow
def test_host_seam_routes_every_placement_operation(tmp_path):
    """The Host abstraction (reference: ConnectionManager.kt's remote-host
    placement) carries EVERY file write, log read and spawn — the loadtest
    harness runs unchanged through it, so an SSH host only has to
    implement the same four methods."""
    from corda_tpu.testing.driver import Driver, LocalHost

    class CountingHost(LocalHost):
        name = "counting-localhost"

        def __init__(self):
            self.calls = {"mkdir": 0, "write_file": 0, "read_text": 0,
                          "spawn": 0}

        def mkdir(self, path):
            self.calls["mkdir"] += 1
            return super().mkdir(path)

        def write_file(self, path, text):
            self.calls["write_file"] += 1
            return super().write_file(path, text)

        def read_text(self, path):
            self.calls["read_text"] += 1
            return super().read_text(path)

        def spawn(self, argv, log_path, cwd, env):
            self.calls["spawn"] += 1
            return super().spawn(argv, log_path, cwd, env)

    host = CountingHost()
    d = Driver(tmp_path, host=host)
    try:
        node = d.start_node("Seam", rpc=True)
        assert node.host is host
        rpc = node.rpc("demo", "s3cret")
        assert rpc.call("node_identity") is not None
        rpc.close()
        node.kill()
        reborn = d.restart_node(node)
        assert reborn.host is host and reborn.address is not None
    finally:
        d.stop_all()
    assert host.calls["mkdir"] == 1
    assert host.calls["write_file"] == 1
    assert host.calls["spawn"] == 2      # start + restart
    assert host.calls["read_text"] > 0   # banner polling reads the log
