"""Durability plane (ISSUE round 14): corruption detection + self-healing.

Covers the acceptance list end to end:

* CRC32C framing (reference vector, chained updates) and the in-place
  upgrade path — a pre-durability store opens cleanly, its rows verify as
  legacy (NULL crc) until fsck/scrub backfills them;
* detection: `python -m corda_tpu.tools.fsck` exit-code/--json contract,
  and the online Scrubber's counters (scans, errors, backfills);
* self-healing raft: a corrupt APPLIED row compacts behind the snapshot
  marker, a corrupt UNAPPLIED suffix truncates to the verified prefix —
  in both cases the member converges back through normal replication
  with exactly-once visible in committed_states, and a leader detecting
  corruption in its own log steps down;
* a damaged InstallSnapshot chunk is discarded, never installed;
* graceful disk exhaustion: a leader that cannot extend its log sheds
  the round (retryable) and cedes leadership; a follower degrades to a
  counted failure reply instead of crashing;
* the maybe_compact crash window (satellite): a crash between the
  log-prefix DELETE and the snapshot marker write must roll back as a
  unit — log indices never silently rebase;
* the seeded `bitrot` chaos plan (slow tier): exactly-once under random
  read-path bit flips + disk-full, with the post-run fsck gate clean.
"""

import json
import os
import sqlite3
import sys

import pytest

from corda_tpu.node.services import integrity as _integrity
from corda_tpu.node.services.persistence import (
    DBCheckpointStorage,
    NodeDatabase,
)
from corda_tpu.node.services.raft import InstallSnapshot, _snapshot_chunk_crc
from corda_tpu.testing import faults
from corda_tpu.tools import fsck

sys.path.insert(0, os.path.dirname(__file__))
from test_raft_group_commit import (  # noqa: E402
    Net,
    cmd,
    elect,
    make_trio,
    settle,
)


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def commit_rounds(net, members, leader, n, tag=b"x"):
    """Commit n commands as n separate log entries (one flush per cmd)."""
    for i in range(n):
        seed = tag + b"-%d" % i
        leader.submit(cmd(seed, b"tx" + seed, b"r" + seed))
        leader.flush_appends()
        net.deliver_all()
    settle(net, members.values())


def committed_refs(member):
    return sorted(
        bytes(r[0]).hex() for r in member.db.conn.execute(
            "SELECT state_ref FROM committed_states").fetchall())


def assert_converged(members, expect_rows):
    """Every member holds the SAME committed set, each ref exactly once."""
    baseline = None
    for m in members.values():
        refs = committed_refs(m)
        assert len(refs) == len(set(refs)) == expect_rows, m.name
        if baseline is None:
            baseline = refs
        assert refs == baseline, m.name


# ---------------------------------------------------------------------------
# CRC frames + legacy upgrade
# ---------------------------------------------------------------------------


def test_crc32c_reference_vector():
    # The Castagnoli check value (RFC 3720 appendix B.4).
    assert _integrity.crc32c(b"123456789") == 0xE3069283
    # Chained updates equal the one-shot digest (the scrubber's chunked walk
    # and the snapshot chunk crc both rely on this).
    assert _integrity.crc32c(
        b"6789", _integrity.crc32c(b"12345")) == 0xE3069283


def test_committed_crc_many_matches_scalar_python_path():
    """Round 18 columnar commit: the batched CRC — native _ccommit when
    the toolchain built it, pure-Python fallback otherwise — must be
    bit-identical to the scalar ``committed_crc`` the scrubber verifies
    rows against. A divergence would make every pipelined commit look
    corrupt on the next scrub pass."""
    import random

    rng = random.Random(0x18)
    pairs = [(b"123456789", b"")]  # the RFC 3720 check value seeds chain
    pairs += [(rng.randbytes(rng.randrange(1, 64)),
               rng.randbytes(rng.randrange(1, 64))) for _ in range(64)]
    got = _integrity.committed_crc_many(pairs)
    assert got == [_integrity.committed_crc(r, c) for r, c in pairs]
    assert _integrity.committed_crc_many([]) == []


def test_committed_crc_many_python_fallback_parity(monkeypatch):
    """Force the pure-Python leg and (when available) compare it against
    the native core directly — the two implementations must agree on the
    same batch regardless of which one ``_load_ccommit`` picked."""
    pairs = [(b"ref-%d" % i, b"tx-%d" % (i % 3)) for i in range(17)]
    native = _integrity._load_ccommit()
    monkeypatch.setattr(_integrity, "_ccommit", False)  # fallback leg
    fallback = _integrity.committed_crc_many(pairs)
    assert fallback == [_integrity.committed_crc(r, c) for r, c in pairs]
    if native:
        assert list(native.committed_crc_many(pairs)) == fallback


def test_log_crc_binds_index_term_and_bytes():
    base = _integrity.log_crc(7, 3, b"entry")
    assert _integrity.log_crc(8, 3, b"entry") != base
    assert _integrity.log_crc(7, 4, b"entry") != base
    assert _integrity.log_crc(7, 3, b"Entry") != base


def _legacy_store(path):
    """A pre-durability sqlite store: same tables, NO crc columns."""
    conn = sqlite3.connect(str(path))
    conn.executescript("""
        CREATE TABLE settings (key TEXT PRIMARY KEY, value TEXT);
        CREATE TABLE raft_log (idx INTEGER PRIMARY KEY, term INTEGER,
                               blob BLOB);
        CREATE TABLE checkpoints (run_id BLOB PRIMARY KEY, blob BLOB);
        CREATE TABLE committed_states (state_ref BLOB PRIMARY KEY,
                                       consuming BLOB);
        CREATE TABLE reserved_states (state_ref BLOB PRIMARY KEY,
                                      tx_id BLOB, expires_at REAL);
    """)
    conn.execute("INSERT INTO raft_log VALUES (1, 1, ?)", (b"old-entry",))
    conn.execute("INSERT INTO checkpoints VALUES (?, ?)",
                 (b"\x0a" * 8, b"old-checkpoint"))
    conn.execute("INSERT INTO committed_states VALUES (?, ?)",
                 (b"\x11" * 33, b"\x22" * 32))
    conn.commit()
    conn.close()


def test_legacy_store_verifies_clean_then_backfills(tmp_path):
    db = tmp_path / "legacy.db"
    _legacy_store(db)
    # Detection pass: legacy rows are clean (NULL crc = unverified), never
    # false-positive corrupt.
    report = fsck.fsck_db(db)
    assert report["clean"] and report["corrupt"] == 0
    assert report["legacy"] == 3
    # Repair pass backfills every legacy frame in place.
    report = fsck.fsck_db(db, repair=True)
    assert report["clean"] and report["backfilled"] == 3
    conn = sqlite3.connect(str(db))
    (nulls,) = conn.execute(
        "SELECT COUNT(*) FROM raft_log WHERE crc IS NULL").fetchone()
    assert nulls == 0
    conn.close()
    report = fsck.fsck_db(db)
    assert report["clean"] and report["legacy"] == 0


def test_node_database_opens_legacy_store_in_place(tmp_path):
    path = tmp_path / "node.db"
    _legacy_store(path)
    db = NodeDatabase(path)  # must not raise: in-place schema upgrade
    cols = {r[1] for r in db.conn.execute(
        "PRAGMA table_info(raft_log)").fetchall()}
    assert "crc" in cols
    # The legacy row survived untouched, crc NULL until a scrub backfills.
    (blob, crc) = db.conn.execute(
        "SELECT blob, crc FROM raft_log WHERE idx = 1").fetchone()
    assert bytes(blob) == b"old-entry" and crc is None
    db.close()


# ---------------------------------------------------------------------------
# Checkpoint corruption -> quarantine
# ---------------------------------------------------------------------------


def test_checkpoint_crc_mismatch_quarantined_before_decode(tmp_path):
    db = NodeDatabase(tmp_path / "node.db")
    cs = DBCheckpointStorage(db)
    cs.update_checkpoint(b"\x01" * 8, b"good-checkpoint")
    cs.update_checkpoint(b"\x02" * 8, b"doomed-checkpoint")
    db.conn.execute("UPDATE checkpoints SET blob = ? WHERE run_id = ?",
                    (b"damaged!", b"\x02" * 8))
    db.conn.commit()
    before = _integrity.stats().get("checkpoints_quarantined", 0)
    items = cs.items()
    assert [rid for rid, _ in items] == [b"\x01" * 8]
    (n,) = db.conn.execute(
        "SELECT COUNT(*) FROM quarantine WHERE kind = 'checkpoint'"
    ).fetchone()
    assert n == 1
    assert _integrity.stats()["checkpoints_quarantined"] == before + 1
    db.close()


def test_smm_restore_quarantines_undecodable_checkpoint(tmp_path):
    """A blob whose crc verifies but whose bytes no longer decode is caught
    at the codec layer: counted, quarantined, restore proceeds."""
    import types

    from corda_tpu.node.statemachine import StateMachineManager

    db = NodeDatabase(tmp_path / "node.db")
    cs = DBCheckpointStorage(db)
    # Written through the storage, so its crc frame is VALID — the damage
    # model here is an encoding-era blob, not bitrot.
    cs.update_checkpoint(b"\x03" * 8, b"\x00not-a-codec-frame")
    smm = StateMachineManager(
        None, types.SimpleNamespace(add_message_handler=lambda *a: None),
        checkpoint_storage=cs)
    smm._restore_checkpoints()
    assert smm.metrics["checkpoints_quarantined"] == 1
    assert smm.flows == {}
    assert cs.items() == []  # moved out of the checkpoints table
    (n,) = db.conn.execute("SELECT COUNT(*) FROM quarantine").fetchone()
    assert n == 1
    db.close()


# ---------------------------------------------------------------------------
# Self-healing raft log
# ---------------------------------------------------------------------------


def corrupt_log_row(member, idx, blob=b"bitrot!"):
    member.db.conn.execute(
        "UPDATE raft_log SET blob = ? WHERE idx = ?", (blob, idx))
    member.db.conn.commit()
    # Detection is the sqlite READ path; drop the in-memory mirrors the
    # way a restart would.
    member._entry_cache.clear()
    member._blob_cache.clear()


def test_follower_corrupt_applied_row_compacts_and_converges(tmp_path):
    """THE acceptance scenario: a follower with a corrupted log suffix
    detects, heals, and converges — exactly once, integrity_errors > 0."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)
    commit_rounds(net, members, leader, 3, tag=b"pre")

    follower = members["B"]
    assert follower.last_applied == 3
    corrupt_log_row(follower, 2)
    # First read through the store detects the mismatch and heals: the
    # row's effects are already applied, so the prefix compacts behind a
    # snapshot marker (corruption becomes a LAGGING member, not a
    # diverged one).
    follower._log_entries_from(1)
    assert follower.metrics["integrity_errors"] == 1
    assert follower.metrics["log_truncations"] == 1
    assert follower.snapshot_index == 3
    (n,) = follower.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log WHERE idx <= 3").fetchone()
    assert n == 0

    # Normal replication resumes on top of the healed store.
    commit_rounds(net, members, leader, 3, tag=b"post")
    assert_converged(members, expect_rows=6)
    stamp = follower.stamp()
    assert stamp["integrity_errors"] > 0  # the acceptance counter
    json.dumps(stamp)


def test_follower_corrupt_unapplied_suffix_truncates(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)
    commit_rounds(net, members, leader, 2, tag=b"pre")

    follower = members["B"]
    assert follower.last_applied == 2
    # An unapplied suffix row whose frame doesn't verify (torn write).
    follower.db.conn.execute(
        "INSERT INTO raft_log (idx, term, blob, crc) VALUES (?, ?, ?, ?)",
        (3, follower.term, b"torn-write", 1))
    follower.db.conn.commit()
    follower._entry_cache.clear()
    follower._blob_cache.clear()

    follower._verified_log_rows(3, 4)
    assert follower.metrics["integrity_errors"] == 1
    assert (follower.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log WHERE idx >= 3").fetchone())[0] == 0
    assert follower.commit_index == 2  # clamped to the verified prefix

    commit_rounds(net, members, leader, 2, tag=b"post")
    assert_converged(members, expect_rows=4)


def test_leader_corrupt_row_steps_down(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)
    commit_rounds(net, members, leader, 2, tag=b"pre")

    corrupt_log_row(leader, 1)
    leader._log_entries_from(1)
    # Its log can no longer vouch for the range it was replicating: cede.
    assert leader.role == "follower"
    assert leader.metrics["leader_stepdowns"] == 1
    assert leader.metrics["integrity_errors"] == 1

    new = members["B"]
    elect(net, new, t)
    commit_rounds(net, members, new, 2, tag=b"post")
    assert_converged(members, expect_rows=4)


def test_install_snapshot_bad_chunk_crc_discarded(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    follower = members["B"]
    entries = ((b"\x31" * 33, b"\x00" * 32), (b"\x32" * 33, b"\x01" * 32))

    bad = InstallSnapshot(term=1, leader="A", last_included_index=5,
                          last_included_term=1, entries=entries,
                          crc=_snapshot_chunk_crc(entries) ^ 1)
    follower._on_install_snapshot(bad, "A")
    assert follower.metrics["integrity_errors"] == 1
    assert follower.last_applied == 0  # nothing installed

    good = InstallSnapshot(term=1, leader="A", last_included_index=5,
                           last_included_term=1, entries=entries,
                           crc=_snapshot_chunk_crc(entries))
    follower._on_install_snapshot(good, "A")
    assert follower.last_applied == 5
    rows = follower.db.conn.execute(
        "SELECT state_ref, consuming, crc FROM committed_states").fetchall()
    assert len(rows) == 2
    for ref, con, crc in rows:  # installed rows carry fresh frames
        assert crc is not None
        assert int(crc) == _integrity.committed_crc(bytes(ref), bytes(con))


# ---------------------------------------------------------------------------
# Graceful disk exhaustion
# ---------------------------------------------------------------------------


def test_disk_full_leader_sheds_round_and_steps_down(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)

    faults.arm(faults.FaultPlan(7, [
        faults.FaultRule("disk.full", "full", max_fires=1)]))
    leader.submit(cmd(b"s1", b"t1", b"r1"))
    leader.flush_appends()
    faults.disarm()

    # The seal failed before anything durable: shed retryable, cede.
    assert leader.metrics["disk_degraded"] == 1
    assert leader.role == "follower"
    assert leader.decided[b"r1"].ok is False
    assert leader.decided[b"r1"].conflict is None  # retryable, not final
    (n,) = leader.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log").fetchone()
    assert n == 0

    # The disk "recovered": re-elect and the resubmission commits.
    leader.decided.clear()
    elect(net, leader, t)
    commit_rounds(net, members, leader, 1, tag=b"retry")
    assert_converged(members, expect_rows=1)


def test_disk_full_follower_degrades_then_catches_up(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)

    # Event 1 at disk.full is the leader's own seal — skip it; the fire
    # lands on the FIRST follower append.
    faults.arm(faults.FaultPlan(7, [
        faults.FaultRule("disk.full", "full", after=1, max_fires=1)]))
    leader.submit(cmd(b"s1", b"t1", b"r1"))
    leader.flush_appends()
    net.deliver_all()
    faults.disarm()

    degraded = [m for m in members.values()
                if m.metrics["disk_degraded"] == 1]
    assert len(degraded) == 1 and degraded[0] is not leader

    # Replication retries after the failure reply; everyone converges.
    settle(net, members.values())
    assert_converged(members, expect_rows=1)
    assert leader.decided[b"r1"].ok is True


# ---------------------------------------------------------------------------
# maybe_compact crash window (satellite)
# ---------------------------------------------------------------------------


class _CrashingConn:
    """Connection proxy that raises at a chosen statement — the shape of a
    crash between two statements of one logical transaction."""

    def __init__(self, real, trigger):
        self._real = real
        self._trigger = trigger

    def execute(self, sql, *args):
        if self._trigger(sql, args):
            raise RuntimeError("injected crash")
        return self._real.execute(sql, *args)

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_maybe_compact_crash_window_never_rebases_indices(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)
    commit_rounds(net, members, leader, 8, tag=b"c")
    assert leader.last_applied == 8
    leader.COMPACT_THRESHOLD = 4  # instance override: compact upto 6

    real = leader.db._conn
    leader.db._conn = _CrashingConn(
        real, lambda sql, args: sql.startswith(
            "INSERT OR REPLACE INTO settings")
        and args and args[0][0] == "raft_snapshot_index")
    try:
        with pytest.raises(RuntimeError, match="injected crash"):
            leader.maybe_compact()
    finally:
        leader.db._conn = real

    # The half-compaction (prefix DELETE without its marker) rolled back
    # as a unit: nothing rebased, nothing half-durable.
    (lo, n) = leader.db.conn.execute(
        "SELECT MIN(idx), COUNT(*) FROM raft_log").fetchone()
    assert (lo, n) == (1, 8)
    assert leader.snapshot_index == 0
    assert leader.db.conn.execute(
        "SELECT value FROM settings WHERE key = 'raft_snapshot_index'"
    ).fetchone() is None
    # An unrelated later commit must not flush the dead prefix-DELETE: a
    # FRESH connection sees the full log and no marker.
    leader.db.set_setting("unrelated", "1")
    probe = sqlite3.connect(leader.db.path)
    assert probe.execute(
        "SELECT MIN(idx), COUNT(*) FROM raft_log").fetchone() == (1, 8)
    assert probe.execute(
        "SELECT value FROM settings WHERE key = 'raft_snapshot_index'"
    ).fetchone() is None
    probe.close()

    # Without the crash the same compaction succeeds — indices preserved
    # (remaining rows keep their original idx above the marker).
    leader.maybe_compact()
    assert leader.snapshot_index == 6
    assert leader.db.conn.execute(
        "SELECT MIN(idx), COUNT(*) FROM raft_log").fetchone() == (7, 2)


# ---------------------------------------------------------------------------
# fsck CLI + scrubber
# ---------------------------------------------------------------------------


def _framed_store(path, n=8, last_applied=4):
    """A store with n crc-framed raft rows and one committed row."""
    db = NodeDatabase(path)
    # raft_log belongs to the consensus schema, created at member start.
    db.conn.execute(
        "CREATE TABLE IF NOT EXISTS raft_log (idx INTEGER PRIMARY KEY, "
        "term INTEGER NOT NULL, blob BLOB NOT NULL, crc INTEGER)")
    for i in range(1, n + 1):
        blob = b"entry-%04d" % i
        db.conn.execute(
            "INSERT INTO raft_log (idx, term, blob, crc) VALUES (?,?,?,?)",
            (i, 1, blob, _integrity.log_crc(i, 1, blob)))
    ref, con = b"\x11" * 33, b"\x22" * 32
    db.conn.execute(
        "INSERT INTO committed_states (state_ref, consuming, crc) "
        "VALUES (?, ?, ?)", (ref, con, _integrity.committed_crc(ref, con)))
    db.conn.commit()
    db.set_setting("raft_last_applied", str(last_applied))
    db.close()


def test_fsck_cli_exit_codes_and_json(tmp_path, capsys):
    _framed_store(tmp_path / "node.db")
    assert fsck.main([str(tmp_path)]) == 0
    capsys.readouterr()

    conn = sqlite3.connect(str(tmp_path / "node.db"))
    conn.execute("UPDATE raft_log SET blob = ? WHERE idx = 6", (b"damaged",))
    conn.commit()
    conn.close()

    assert fsck.main([str(tmp_path), "--json"]) == 1
    out = capsys.readouterr().out.strip()
    assert "\n" not in out  # one-line JSON
    report = json.loads(out)
    assert report["clean"] is False
    assert report["corrupt"] == 1
    assert report["stores"] == 1


def test_fsck_repair_truncates_suffix_and_compacts_prefix(tmp_path, capsys):
    _framed_store(tmp_path / "node.db", n=8, last_applied=4)
    conn = sqlite3.connect(str(tmp_path / "node.db"))
    conn.execute("UPDATE raft_log SET blob = ? WHERE idx = 2", (b"bad",))
    conn.execute("UPDATE raft_log SET blob = ? WHERE idx = 6", (b"bad",))
    conn.commit()
    conn.close()

    assert fsck.main([str(tmp_path)]) == 1
    capsys.readouterr()
    # Raft damage is repairable offline: applied prefix (idx 2 <= 4)
    # compacts behind the marker, unapplied suffix (idx 6 > 4) truncates.
    assert fsck.main([str(tmp_path), "--repair"]) == 0
    capsys.readouterr()

    conn = sqlite3.connect(str(tmp_path / "node.db"))
    idxs = [r[0] for r in conn.execute(
        "SELECT idx FROM raft_log ORDER BY idx").fetchall()]
    assert idxs == [5]  # original index preserved — never rebased to 1
    (marker,) = conn.execute(
        "SELECT value FROM settings WHERE key = 'raft_snapshot_index'"
    ).fetchone()
    assert marker == "4"
    conn.close()
    assert fsck.main([str(tmp_path)]) == 0


def test_fsck_repair_quarantines_checkpoint_reports_ledger(tmp_path, capsys):
    db = NodeDatabase(tmp_path / "node.db")
    DBCheckpointStorage(db).update_checkpoint(b"\x05" * 8, b"checkpoint")
    ref, con = b"\x11" * 33, b"\x22" * 32
    db.conn.execute(
        "INSERT INTO committed_states (state_ref, consuming, crc) "
        "VALUES (?, ?, ?)", (ref, con, _integrity.committed_crc(ref, con)))
    db.conn.execute("UPDATE checkpoints SET blob = ?", (b"damaged",))
    db.conn.commit()
    db.close()

    assert fsck.main([str(tmp_path), "--repair"]) == 0
    capsys.readouterr()
    conn = sqlite3.connect(str(tmp_path / "node.db"))
    assert conn.execute("SELECT COUNT(*) FROM checkpoints").fetchone() == (0,)
    assert conn.execute(
        "SELECT COUNT(*) FROM quarantine WHERE kind = 'checkpoint'"
    ).fetchone() == (1,)

    # A corrupt LEDGER row is never auto-repaired (un-spending an input is
    # worse than reporting): --repair still exits dirty.
    conn.execute("UPDATE committed_states SET consuming = ?", (b"\x33" * 32,))
    conn.commit()
    conn.close()
    assert fsck.main([str(tmp_path), "--repair"]) == 1
    capsys.readouterr()
    probe = sqlite3.connect(str(tmp_path / "node.db"))
    (n,) = probe.execute("SELECT COUNT(*) FROM committed_states").fetchone()
    assert n == 1  # reported, not deleted
    probe.close()


def test_scrubber_backfills_legacy_and_counts_corruption(tmp_path):
    from corda_tpu.node.services.integrity import Scrubber

    path = tmp_path / "node.db"
    _framed_store(path, n=6, last_applied=6)
    conn = sqlite3.connect(str(path))
    # One legacy row (crc NULL) and one corrupt row.
    conn.execute("UPDATE raft_log SET crc = NULL WHERE idx = 1")
    conn.execute("UPDATE raft_log SET blob = ? WHERE idx = 3", (b"rot",))
    conn.commit()
    conn.close()

    scrubber = Scrubber(path, rows_per_s=1e6, node_name="test")
    scrubber.run_pass(repair=True)
    stats = scrubber.stats()
    assert stats["scrub_passes"] == 1
    assert stats["integrity_scans"] >= 7  # 6 raft rows + 1 committed
    assert stats["crc_backfilled"] == 1
    assert stats["integrity_errors"] == 1
    # The backfill is durable; the corrupt row is counted every pass.
    conn = sqlite3.connect(str(path))
    assert conn.execute(
        "SELECT COUNT(*) FROM raft_log WHERE crc IS NULL").fetchone() == (0,)
    conn.close()
    scrubber.run_pass(repair=True)
    stats = scrubber.stats()
    assert stats["crc_backfilled"] == 1  # nothing left to backfill
    assert stats["integrity_errors"] == 2
    # node_metrics surface: plain JSON types, scrubber counters merged.
    json.dumps(_integrity.stats(scrubber))


def test_scrub_and_repair_trace_stages_registered():
    from corda_tpu.obs.stages import DIRECT_STAGES, SPAN_NAMES, STAGES

    for stage in ("scrub", "repair"):
        assert stage in DIRECT_STAGES
        assert stage in STAGES
        assert stage in SPAN_NAMES


def test_bitrot_plan_is_builtin():
    plan = faults.builtin_plan("bitrot")
    points = {r.point for r in plan.rules}
    assert points == {"disk.corrupt", "disk.full"}


# ---------------------------------------------------------------------------
# Cluster soak (real TCP + sqlite raft cluster; slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bitrot_chaos_exactly_once_with_clean_fsck(tmp_path):
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    result = run_chaos_loadtest(
        plan="bitrot", n_tx=60, rate_tx_s=80.0,
        base_dir=str(tmp_path), max_seconds=120.0)
    assert result.exactly_once, result.to_json()
    # Injected bit-flips live on READ paths only — the stored bytes stay
    # intact, so the post-run store audit must verify clean.
    assert result.fsck_clean is True, result.to_json()
    assert "integrity_errors" in json.loads(result.to_json())
