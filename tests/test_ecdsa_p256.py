"""ECDSA P-256 oracle conformance + mixed-scheme provider routing.

The oracle (corda_tpu/crypto/ref_ecdsa_p256.py) must agree with OpenSSL
(the `cryptography` wheel) on accepts AND rejects — golden vectors plus
mutation fuzzing — and the provider seam must route mixed ed25519 /
ecdsa-p256 batches correctly (reference scheme usage:
core/.../crypto/X509Utilities.kt:44-48).
"""

import hashlib

import numpy as np
import pytest

# The oracle-vs-OpenSSL conformance claim needs the wheel; absent it the
# module is a clean SKIP (reason in the report), not a collection ERROR.
pytest.importorskip(
    "cryptography",
    reason="the 'cryptography' wheel is not installed on this interpreter "
           "— the P-256 conformance oracle cross-checks against it")

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)
from cryptography.hazmat.primitives import hashes as c_hashes
from cryptography.hazmat.primitives.serialization import (
    Encoding,
    PublicFormat,
)

from corda_tpu.crypto import ref_ecdsa_p256 as oracle
from corda_tpu.crypto import ref_ed25519


def _keypair(i: int = 1):
    key = ec.derive_private_key(0x1000 + i, ec.SECP256R1())
    pub = key.public_key().public_bytes(
        Encoding.X962, PublicFormat.UncompressedPoint)
    return key, pub


def _openssl_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    try:
        ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256R1(), pub).verify(sig, msg, ec.ECDSA(c_hashes.SHA256()))
        return True
    except Exception:
        return False


def test_golden_accepts():
    for i in range(4):
        key, pub = _keypair(i)
        msg = b"tx-%d" % i
        sig = key.sign(msg, ec.ECDSA(c_hashes.SHA256()))
        assert oracle.verify(pub, msg, sig)
        assert _openssl_verify(pub, msg, sig)


def test_golden_rejects():
    key, pub = _keypair()
    msg = b"message"
    sig = key.sign(msg, ec.ECDSA(c_hashes.SHA256()))
    r, s = decode_dss_signature(sig)
    cases = [
        (pub, b"other", sig),                        # wrong message
        (pub, msg, encode_dss_signature(r ^ 1, s)),  # r tampered
        (pub, msg, encode_dss_signature(r, s ^ 1)),  # s tampered
        (pub, msg, b""),                             # empty sig
        (pub, msg, b"\x30\x02\x02\x00"),             # garbage DER
        (pub, msg, sig[:-1]),                        # truncated DER
        (pub, msg, sig + b"\x00"),                   # trailing bytes
        (pub[:-1], msg, sig),                        # truncated key
        (b"\x02" + pub[1:], msg, sig),               # compressed prefix
        (pub[:1] + b"\x00" * 64, msg, sig),          # off-curve point
    ]
    for p, m, sg in cases:
        assert not oracle.verify(p, m, sg), (p[:2], m, sg[:4])
        assert not _openssl_verify(p, m, sg)
    # range violations: r/s = 0 or n encode fine but must reject
    assert not oracle.verify(pub, msg, encode_dss_signature(0, s))
    assert not oracle.verify(pub, msg, encode_dss_signature(r, oracle.N))


def test_high_s_accepted_like_jca():
    # No low-s rule in JCA/BC or OpenSSL verify: (r, n - s) also verifies.
    key, pub = _keypair()
    msg = b"mutable-s"
    sig = key.sign(msg, ec.ECDSA(c_hashes.SHA256()))
    r, s = decode_dss_signature(sig)
    high = encode_dss_signature(r, oracle.N - s)
    assert oracle.verify(pub, msg, high)
    assert _openssl_verify(pub, msg, high)


def test_mutation_fuzz_agrees_with_openssl():
    import random

    rng = random.Random(5)
    key, pub = _keypair()
    msg = b"fuzz-me"
    sig = bytearray(key.sign(msg, ec.ECDSA(c_hashes.SHA256())))
    agreements = 0
    for _ in range(60):
        mutated = bytearray(sig)
        for _ in range(rng.randrange(1, 3)):
            mutated[rng.randrange(len(mutated))] ^= 1 << rng.randrange(8)
        got = oracle.verify(pub, msg, bytes(mutated))
        want = _openssl_verify(pub, msg, bytes(mutated))
        assert got == want, (bytes(mutated).hex(), got, want)
        agreements += 1
    assert agreements == 60


def test_fast_path_bit_identical_to_oracle():
    """fast_ecdsa_p256 (OpenSSL behind the oracle's structural gate) must
    agree with the oracle on every golden accept, every golden reject, and
    a mutation-fuzz corpus — the accept-set equivalence argument in its
    module docstring, checked."""
    from corda_tpu.crypto import fast_ecdsa_p256 as fast

    assert fast.available()
    key, pub = _keypair()
    msg = b"gate-me"
    sig = key.sign(msg, ec.ECDSA(c_hashes.SHA256()))
    r, s = decode_dss_signature(sig)
    cases = [
        (pub, msg, sig),                              # accept
        (pub, msg, encode_dss_signature(r, oracle.N - s)),  # high-s accept
        (pub, b"other", sig),
        (pub, msg, encode_dss_signature(r ^ 1, s)),
        (pub, msg, b""),
        (pub, msg, sig[:-1]),
        (pub, msg, sig + b"\x00"),
        (pub[:-1], msg, sig),
        (b"\x02" + pub[1:], msg, sig),                # compressed: oracle rejects
        (pub[:1] + b"\x00" * 64, msg, sig),           # off-curve
        (pub, msg, encode_dss_signature(0, s)),       # r = 0
        (pub, msg, encode_dss_signature(r, oracle.N)),  # s = n
    ]
    import random

    rng = random.Random(11)
    mutated = bytearray(sig)
    for _ in range(40):
        m = bytearray(mutated)
        m[rng.randrange(len(m))] ^= 1 << rng.randrange(8)
        cases.append((pub, msg, bytes(m)))
    for p, m, sg in cases:
        assert fast.verify(p, m, sg) == oracle.verify(p, m, sg), (
            p[:2], m, sg[:6])


def test_fast_path_is_fast():
    """The production dispatch must run P-256 at OpenSSL speed (round-4
    weak #6: ~1 ms/op pure-Python on the hot path). 50 verifies through
    the provider in well under what 50 oracle calls would take."""
    import time

    from corda_tpu.crypto.provider import CpuVerifier, VerifyJob

    key, pub = _keypair()
    jobs = []
    for i in range(50):
        msg = b"tls-%d" % i
        jobs.append(VerifyJob(pub, msg, key.sign(
            msg, ec.ECDSA(c_hashes.SHA256())), scheme="ecdsa-p256"))
    v = CpuVerifier()
    v.verify_batch(jobs[:2])  # warm key cache
    t0 = time.perf_counter()
    out = v.verify_batch(jobs)
    dt = time.perf_counter() - t0
    assert out.all()
    # Oracle alone runs ~1 ms/op => ~50 ms; OpenSSL does this in ~2-5 ms.
    # Generous bound so a loaded CI core never flakes.
    assert dt < 0.6, f"P-256 dispatch took {dt * 1e3:.1f} ms for 50 ops"


def test_mixed_scheme_batch_routes_by_scheme():
    from corda_tpu.crypto.provider import CpuVerifier, JaxVerifier, VerifyJob

    ec_key, ec_pub = _keypair()
    ec_msg = b"tls-handshake-blob"
    ec_sig = ec_key.sign(ec_msg, ec.ECDSA(c_hashes.SHA256()))

    ed_seed = b"\x21" * 32
    ed_pub = ref_ed25519.public_key(ed_seed)
    ed_msg = hashlib.sha256(b"ledger-tx").digest()
    ed_sig = ref_ed25519.sign(ed_seed, ed_msg)

    jobs = [
        VerifyJob(ed_pub, ed_msg, ed_sig),                       # ok
        VerifyJob(ec_pub, ec_msg, ec_sig, scheme="ecdsa-p256"),  # ok
        VerifyJob(ed_pub, ed_msg, ec_sig),                       # cross: bad
        VerifyJob(ec_pub, ec_msg, ed_sig, scheme="ecdsa-p256"),  # cross: bad
        VerifyJob(ed_pub, ed_msg, ed_sig, scheme="rsa-4096"),    # unknown
        VerifyJob(ec_pub, b"other", ec_sig, scheme="ecdsa-p256"),
    ]
    want = [True, True, False, False, False, False]
    for verifier in (CpuVerifier(), JaxVerifier()):
        got = verifier.verify_batch(jobs)
        assert isinstance(got, np.ndarray)
        assert got.tolist() == want, (verifier.name, got.tolist())
