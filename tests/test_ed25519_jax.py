"""Golden-vector conformance: the JAX kernel vs the Python oracle.

Every case asserts kernel(x) == oracle(x) — the oracle
(corda_tpu/crypto/ref_ed25519.py) defines the authoritative accept set
matching the reference's EdDSAEngine behaviour (reference:
core/src/main/kotlin/net/corda/core/crypto/CryptoUtilities.kt:90-96).
"""

import numpy as np
import pytest

from corda_tpu.crypto import ref_ed25519 as ref
from corda_tpu.ops import ed25519_jax as kernel

rng = np.random.default_rng(99)


def _keypair(i):
    seed = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
    return seed, ref.public_key(seed)


def _flip(b: bytes, idx: int, bit: int = 1) -> bytes:
    out = bytearray(b)
    out[idx] ^= bit
    return bytes(out)


def _run(cases):
    """cases: list of (pk, msg, sig). Assert kernel matches oracle per case."""
    pks = [c[0] for c in cases]
    msgs = [c[1] for c in cases]
    sigs = [c[2] for c in cases]
    got = kernel.verify_batch(pks, msgs, sigs)
    want = [ref.verify(pk, m, s) for pk, m, s in cases]
    assert got.tolist() == want, list(zip(got.tolist(), want))
    return want


def test_valid_signatures_accept():
    cases = []
    for i in range(8):
        seed, pk = _keypair(i)
        msg = bytes(rng.integers(0, 256, int(rng.integers(0, 200)), dtype=np.uint8))
        cases.append((pk, msg, ref.sign(seed, msg)))
    want = _run(cases)
    assert all(want)  # sanity: oracle accepts its own signatures


def test_corruptions_reject_and_match_oracle():
    seed, pk = _keypair(0)
    msg = b"notarise me"
    sig = ref.sign(seed, msg)
    cases = [
        (pk, msg, sig),                       # control: valid
        (pk, msg + b"x", sig),                # message tampered
        (pk, msg, _flip(sig, 0)),             # R corrupted
        (pk, msg, _flip(sig, 40)),            # S corrupted
        (_flip(pk, 3), msg, sig),             # pubkey corrupted
        (pk, b"", sig),                       # wrong (empty) message
        (pk, msg, _flip(sig, 63, 0x80)),      # S high bit set (s >= 2^255)
    ]
    want = _run(cases)
    assert want[0] is True and not any(want[1:])


def test_s_plus_L_accepted_no_range_check():
    # The era's library does not range-check S: s+L verifies the same point.
    seed, pk = _keypair(1)
    msg = b"malleable"
    sig = ref.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    s2 = s + ref.L
    assert s2 < 1 << 256
    sig2 = sig[:32] + s2.to_bytes(32, "little")
    want = _run([(pk, msg, sig2)])
    assert want == [True]


def _small_y_point():
    """A curve point with y < 19, so y+p still fits in 255 bits."""
    for y in range(19):
        x = ref._recover_x(y, 0)
        if x is not None:
            return (x, y)
    raise AssertionError("no small-y point found")


def test_noncanonical_A_encoding_matches_oracle():
    # y >= p in the pubkey encoding: decompression silently reduces mod p.
    pt = _small_y_point()
    pk_canon = ref.compress(pt)
    n = int.from_bytes(pk_canon, "little")
    pk_noncanon = int.to_bytes(n + ref.P, 32, "little")
    msg = b"m"
    # No private key for this point; craft an (invalid) signature and just
    # require kernel == oracle on both encodings.
    sig = bytes(64)
    _run([(pk_canon, msg, sig), (pk_noncanon, msg, sig)])


def test_noncanonical_R_rejected_by_byte_compare():
    seed, pk = _keypair(2)
    msg = b"R games"
    sig = ref.sign(seed, msg)
    r = int.from_bytes(sig[:32], "little")
    if (r & ((1 << 255) - 1)) < 19:  # astronomically unlikely; guard anyway
        pytest.skip("R is a small-y encoding")
    # Perturb R to a non-canonical encoding of the SAME point where possible
    # is not generally doable; instead check that an R with y >= p rejects.
    pt = _small_y_point()
    bad_r = int.to_bytes(int.from_bytes(ref.compress(pt), "little") + ref.P,
                         32, "little")
    sig2 = bad_r + sig[32:]
    want = _run([(pk, msg, sig2)])
    assert want == [False]


def test_invalid_point_rejects():
    # Find a y that is not on the curve.
    for y in range(2, 100):
        if ref._recover_x(y, 0) is None:
            bad_pk = int.to_bytes(y, 32, "little")
            break
    seed, pk = _keypair(3)
    msg = b"x"
    sig = ref.sign(seed, msg)
    want = _run([(bad_pk, msg, sig)])
    assert want == [False]


def test_wrong_lengths_reject_without_raising():
    seed, pk = _keypair(4)
    msg = b"len"
    sig = ref.sign(seed, msg)
    got = kernel.verify_batch([pk[:31], pk, pk], [msg, msg, msg],
                              [sig, sig[:63], sig])
    assert got.tolist() == [False, False, True]


def test_mixed_large_batch():
    cases = []
    for i in range(40):
        seed, pk = _keypair(i)
        msg = bytes([i]) * (i % 7)
        sig = ref.sign(seed, msg)
        if i % 3 == 1:
            sig = _flip(sig, i % 64)
        if i % 5 == 2:
            msg = msg + b"!"
        cases.append((pk, msg, sig))
    _run(cases)


def test_shadow_sampling_detects_kernel_divergence(monkeypatch):
    """SURVEY.md hard part #5: the CPU oracle stays authoritative — a
    diverging kernel result must raise loudly, never pass silently."""
    import numpy as np
    import pytest

    from corda_tpu.crypto import ref_ed25519 as ref
    from corda_tpu.crypto.provider import JaxVerifier, VerifyJob
    from corda_tpu.ops import ed25519_jax

    sk = b"\x17" * 32
    pk = ref.public_key(sk)
    msg = b"shadowed"
    sig = ref.sign(sk, msg)
    jobs = [VerifyJob(pk, msg, sig)]

    # device_min_sigs=0 pins the kernel route: a 1-job batch would
    # otherwise take the host tier, which has no kernel to shadow.
    ok = JaxVerifier(shadow_rate=1.0, device_min_sigs=0).verify_batch(jobs)
    assert ok.tolist() == [True]

    # Sabotage the kernel: flip every verdict. Shadow sampling must catch it.
    real = ed25519_jax.verify_batch
    monkeypatch.setattr(ed25519_jax, "verify_batch",
                        lambda *a, **k: ~real(*a, **k))
    with pytest.raises(RuntimeError, match="divergence"):
        JaxVerifier(shadow_rate=1.0, device_min_sigs=0).verify_batch(jobs)


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_verify_stream_matches_oracle_across_batches(depth):
    """The stream pipeline must return per-batch results in order at every
    pipeline depth, bit-identical to the oracle, including mixed
    valid/invalid rows and varying batch sizes."""
    from corda_tpu.crypto import ref_ed25519 as ref
    from corda_tpu.ops import ed25519_jax

    batches, expects = [], []
    for b, size in enumerate((5, 9, 3)):
        pks, msgs, sigs, expect = [], [], [], []
        for i in range(size):
            sk = bytes([b * 16 + i + 1]) * 32
            pk = ref.public_key(sk)
            m = b"stream-%d-%d" % (b, i)
            s = ref.sign(sk, m)
            ok = (i + b) % 3 != 2
            if not ok:
                s = s[:7] + bytes([s[7] ^ 0x20]) + s[8:]
            pks.append(pk)
            msgs.append(m)
            sigs.append(s)
            expect.append(ok)
        batches.append((pks, msgs, sigs))
        expects.append(expect)

    outs = list(ed25519_jax.verify_stream(iter(batches), bucket=16,
                                      depth=depth))
    assert [o.tolist() for o in outs] == expects


def test_device_hash_path_matches_oracle_for_txid_messages():
    """32-byte messages (tx ids) route through the fully-on-device path
    (SHA-512 challenge + sc_reduce on device, ops/sha512_jax.py). The accept
    set must be bit-identical to the oracle, including malformed keys,
    corrupted signatures, S-malleability and non-canonical encodings."""
    cases = []
    for i in range(6):
        seed, pk = _keypair(100 + i)
        msg = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        sig = ref.sign(seed, msg)
        cases.append((pk, msg, sig))
    seed, pk = _keypair(200)
    msg = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
    sig = ref.sign(seed, msg)
    s2 = int.from_bytes(sig[32:], "little") + ref.L
    cases += [
        (pk, msg, _flip(sig, 1)),             # R corrupted
        (pk, msg, _flip(sig, 45)),            # S corrupted
        (_flip(pk, 7), msg, sig),             # pubkey corrupted
        (pk, bytes(32), sig),                 # wrong message
        (pk, msg, sig[:32] + s2.to_bytes(32, "little")),  # S+L malleable
    ]
    pt = _small_y_point()
    noncanon = int.to_bytes(
        int.from_bytes(ref.compress(pt), "little") + ref.P, 32, "little")
    cases += [(noncanon, bytes(32), bytes(64))]

    # Confirm the device-hash path is what actually runs: the host-hashing
    # packer must NOT be called for all-32-byte batches.
    import unittest.mock as mock

    with mock.patch.object(
            kernel, "precompute_batch",
            side_effect=AssertionError("host hash path used")) as _:
        want = _run(cases)
    assert any(want) and not all(want)


def test_device_and_host_hash_paths_agree():
    pks, msgs, sigs = [], [], []
    for i in range(32):
        seed, pk = _keypair(300 + i)
        m = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        s = ref.sign(seed, m)
        if i % 5 == 4:
            s = _flip(s, i % 64)
        pks.append(pk)
        msgs.append(m)
        sigs.append(s)
    host_arrays, _ = kernel.precompute_batch(pks, msgs, sigs, bucket=32)
    dev_arrays, _ = kernel.precompute_batch_device(pks, msgs, sigs, bucket=32)
    host = np.asarray(kernel.verify_arrays_auto(*host_arrays))
    dev = np.asarray(kernel.verify_arrays_hashed(*dev_arrays))
    assert host.tolist() == dev.tolist()


def test_device_hash_path_rejects_mixed_length_messages():
    # Round-2 advisor finding: messages of mixed length summing to 32*n were
    # silently re-split at 32-byte boundaries and verified against scrambled
    # messages. Each message must be exactly 32 bytes.
    pks, msgs, sigs = [], [], []
    for i in range(2):
        seed, pk = _keypair(400 + i)
        m = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        pks.append(pk)
        msgs.append(m)
        sigs.append(ref.sign(seed, m))
    # 31 + 33 = 64 = 32*2: aggregate length check would pass this.
    msgs = [msgs[0][:31], msgs[1] + b"\x00"]
    with pytest.raises(ValueError, match="32-byte"):
        kernel.precompute_batch_device(pks, msgs, sigs, bucket=32)


def test_pallas_fallback_is_per_call_and_recorded(monkeypatch):
    # Round-3 postmortem: a single transient Pallas failure must demote only
    # its own call (logged + recorded), NOT flip the process to XLA forever.
    from corda_tpu.ops import ed25519_pallas

    kernel.reset_pallas_state()
    kernel._PALLAS_STATE["available"] = True  # pretend a TPU is present
    calls = {"pallas": 0}

    def fake_pallas(a, r, s, h):
        calls["pallas"] += 1
        if calls["pallas"] == 1:
            raise RuntimeError("transient allocator hiccup")
        return "pallas-result"

    monkeypatch.setattr(ed25519_pallas, "verify_arrays_pallas", fake_pallas)
    monkeypatch.setattr(kernel, "verify_arrays", lambda *a: "xla-result")
    arr = np.zeros((8, 1024), np.uint32)
    try:
        out = kernel.verify_arrays_auto(arr, arr, arr, arr)
        assert out == "xla-result"
        assert kernel.last_backend() == "xla"
        assert "transient allocator hiccup" in kernel.last_pallas_error()
        # The very next call retries Pallas and succeeds.
        out = kernel.verify_arrays_auto(arr, arr, arr, arr)
        assert out == "pallas-result"
        assert kernel.last_backend() == "pallas"
        assert kernel._PALLAS_STATE["consecutive_failures"] == 0
        # last_pallas_error stays for attribution even after recovery.
        assert kernel.last_pallas_error() is not None
    finally:
        kernel.reset_pallas_state()


def test_pallas_disabled_after_consecutive_failures(monkeypatch):
    from corda_tpu.ops import ed25519_pallas

    kernel.reset_pallas_state()
    kernel._PALLAS_STATE["available"] = True
    calls = {"pallas": 0}

    def always_fail(a, r, s, h):
        calls["pallas"] += 1
        raise RuntimeError("mosaic regression")

    monkeypatch.setattr(ed25519_pallas, "verify_arrays_pallas", always_fail)
    monkeypatch.setattr(kernel, "verify_arrays", lambda *a: "xla-result")
    arr = np.zeros((8, 1024), np.uint32)
    try:
        for _ in range(kernel.PALLAS_MAX_CONSECUTIVE_FAILURES + 2):
            assert kernel.verify_arrays_auto(arr, arr, arr, arr) == "xla-result"
        # Retried exactly MAX times, then stopped paying the recompile tax.
        assert calls["pallas"] == kernel.PALLAS_MAX_CONSECUTIVE_FAILURES
        assert kernel._PALLAS_STATE["failures_total"] == calls["pallas"]
    finally:
        kernel.reset_pallas_state()


def test_native_pack_parity():
    """The native packer (_cverify.c pack_words) must produce byte-for-byte
    the same word arrays as the numpy path, and reject the same inputs —
    the same authority/fast-path contract as the codec core."""
    import numpy as np
    import pytest

    from corda_tpu.crypto import ref_ed25519 as ref
    from corda_tpu.ops import ed25519_jax

    native = ed25519_jax._cpack_module()
    if native is None:
        pytest.skip("no native toolchain/libcrypto")

    pks, msgs, sigs = [], [], []
    for i in range(37):  # odd size: padding lanes exercised
        seed = bytes([(i % 255) + 1]) * 32
        pks.append(ref.public_key(seed))
        m = (b"pack-%d" % i).ljust(32, b".")
        msgs.append(m)
        sigs.append(ref.sign(seed, m))
    bucket = 64

    raw = native.pack_words(pks, msgs, sigs, bucket)
    got = [np.frombuffer(r, "<u4").reshape(8, bucket) for r in raw]

    m_cat = b"".join(msgs)
    _, _, pk, r_enc, s_raw = ed25519_jax._pack_pk_rs(pks, sigs, 37, bucket)
    m_raw = np.zeros((bucket, 32), np.uint8)
    m_raw[:37] = np.frombuffer(m_cat, np.uint8).reshape(37, 32)
    want = [ed25519_jax._words_of(x) for x in (pk, r_enc, s_raw, m_raw)]
    for g, w, name in zip(got, want, "ARSM"):
        assert np.array_equal(g, w), f"{name} words diverged"

    # Rejection parity: ValueError on a short message / short key / bad sig
    with pytest.raises(ValueError):
        native.pack_words(pks, [b"short"] + msgs[1:], sigs, bucket)
    with pytest.raises(ValueError):
        native.pack_words([b"\x00" * 31] + pks[1:], msgs, sigs, bucket)
    with pytest.raises(ValueError):
        native.pack_words(pks, msgs, [b"\x00" * 63] + sigs[1:], bucket)
    with pytest.raises(ValueError):
        native.pack_words(pks[:-1], msgs, sigs, bucket)  # length mismatch
    with pytest.raises(ValueError):
        native.pack_words(pks, msgs, sigs, 16)  # bucket < n


def test_numpy_fallback_packer_rejects_per_item_like_native(monkeypatch):
    """The numpy fallback of precompute_batch_device must reject malformed
    inputs per-ITEM with the native packer's exact messages and order
    (pk -> msg -> sig), so a host without the native core fails identically
    instead of silently packing garbage lanes."""
    monkeypatch.setattr(kernel, "_CPACK_CACHE", [None])  # force numpy path

    pks, msgs, sigs = [], [], []
    for i in range(4):
        seed, pk = _keypair(500 + i)
        m = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        pks.append(pk)
        msgs.append(m)
        sigs.append(ref.sign(seed, m))

    with pytest.raises(ValueError, match="equal length"):
        kernel.precompute_batch_device(pks[:-1], msgs, sigs, bucket=8)
    with pytest.raises(ValueError, match="bucket smaller than batch"):
        kernel.precompute_batch_device(pks, msgs, sigs, bucket=2)
    with pytest.raises(ValueError, match="pubkeys must be 32 bytes"):
        kernel.precompute_batch_device(
            [b"\x00" * 31] + pks[1:], msgs, sigs, bucket=8)
    with pytest.raises(ValueError, match="32-byte messages"):
        kernel.precompute_batch_device(
            pks, [b"short"] + msgs[1:], sigs, bucket=8)
    with pytest.raises(ValueError, match="sigs must be 64 bytes"):
        kernel.precompute_batch_device(
            pks, msgs, [b"\x00" * 63] + sigs[1:], bucket=8)
    # An item bad in several ways reports its FIRST failure (native order):
    # the pk check fires before the msg check on the same index.
    with pytest.raises(ValueError, match="pubkeys must be 32 bytes"):
        kernel.precompute_batch_device(
            [b"\x00" * 31] + pks[1:], [b"short"] + msgs[1:], sigs, bucket=8)
    # And well-formed input still packs (the happy path stays intact).
    arrays, n = kernel.precompute_batch_device(pks, msgs, sigs, bucket=8)
    assert n == 4 and arrays[0].shape == (8, 8)
