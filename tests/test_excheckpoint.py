"""Typed exceptions survive checkpoint replay with structure intact.

The reference keeps exception fidelity by Kryo-serializing live fibers
(reference: node/.../statemachine/FlowStateMachineImpl.kt:238-261); here the
whitelisted excheckpoint registry carries types + structured payloads through
the replay-checkpoint codec instead.
"""

import pytest

from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.keys import KeyPair, SignatureError
from corda_tpu.crypto.party import Party
from corda_tpu.flows.api import FlowException, FlowSessionException
from corda_tpu.flows.notary import (
    NotaryConflict,
    NotaryException,
    NotarySignaturesMissing,
    NotaryTimestampInvalid,
)
from corda_tpu.node.services.api import (
    ConsumingTx,
    UniquenessConflict,
    UniquenessException,
)
from corda_tpu.node.statemachine import _rebuild_exception
from corda_tpu.serialization.codec import deserialize, serialize
from corda_tpu.utils.excheckpoint import record_exception, rebuild_exception


def _roundtrip(exc):
    """record -> codec serialize -> deserialize -> rebuild, as replay does."""
    entry = record_exception(exc)
    entry2 = deserialize(serialize(entry).bytes)
    return _rebuild_exception(tuple(entry2))


def test_signature_error_keeps_type():
    out = _roundtrip(SignatureError("Signature did not match"))
    assert type(out) is SignatureError
    assert "did not match" in str(out)


def test_signatures_missing_keeps_structure():
    from corda_tpu.transactions.signed import SignaturesMissingException

    key = KeyPair.generate(b"\x07" * 32).public.composite
    exc = SignaturesMissingException({key}, ["notary"], SecureHash.zero())
    out = _roundtrip(exc)
    assert isinstance(out, SignaturesMissingException)
    assert isinstance(out, SignatureError)  # subtype relation preserved
    assert out.missing == {key}
    assert out.descriptions == ["notary"]
    assert out.id == SecureHash.zero()


def test_notary_exception_keeps_error_kind():
    out = _roundtrip(NotaryException(NotaryTimestampInvalid()))
    assert isinstance(out, NotaryException)
    assert isinstance(out.error, NotaryTimestampInvalid)
    # A flow branching on the error kind post-restore behaves as it did live.
    missing = _roundtrip(NotaryException(NotarySignaturesMissing(frozenset())))
    assert isinstance(missing.error, NotarySignaturesMissing)


def test_uniqueness_exception_keeps_conflict_evidence():
    party = Party("Bank A", KeyPair.generate(b"\x01" * 32).public.composite)
    conflict = UniquenessConflict(
        state_history={SecureHash.zero(): ConsumingTx(SecureHash.zero(), 0, party)}
    )
    out = _roundtrip(UniquenessException(conflict))
    assert isinstance(out, UniquenessException)
    assert out.error == conflict


def test_flow_session_exception_type_preserved():
    out = _roundtrip(FlowSessionException("peer rejected"))
    assert type(out) is FlowSessionException


def test_unregistered_type_degrades_to_flow_exception():
    class WeirdError(Exception):
        pass

    out = _roundtrip(WeirdError("boom"))
    assert type(out) is FlowException
    assert "WeirdError" in str(out) and "boom" in str(out)


def test_rebuild_exception_returns_none_for_unknown():
    assert rebuild_exception(("e", "NoSuchType", "msg")) is None


def test_live_verify_failure_keeps_type(net=None):
    """The LIVE (non-replay) path must throw the same typed exception replay
    rebuilds: a missing-signature failure from the batched verifier arrives
    in the flow as SignaturesMissingException, not a generic FlowException."""
    from corda_tpu.crypto.provider import CpuVerifier
    from corda_tpu.flows.api import FlowLogic, register_flow
    from corda_tpu.testing.mock_network import MockNetwork
    from corda_tpu.testing.dummies import DummyContract
    from corda_tpu.transactions.signed import SignaturesMissingException

    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        alice = net.create_node("Alice")
        bob = net.create_node("Bob")

        builder = DummyContract.generate_initial(
            alice.identity.ref(b"\x01"), 1, notary.identity)
        builder.sign_with(alice.key)
        issue = builder.to_signed_transaction()
        alice.record_transaction(issue)
        move = DummyContract.move(issue.tx.out_ref(0), bob.identity.owning_key)
        move.sign_with(bob.key)  # WRONG signer: alice's signature is missing
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        caught = []

        @register_flow
        class CatchTyped(FlowLogic):
            def __init__(self, stx):
                self.stx = stx

            def call(self):
                try:
                    yield self.verify_signatures_batched(self.stx)
                except SignaturesMissingException as e:
                    caught.append(("typed", sorted(map(repr, e.missing))))
                except Exception as e:
                    caught.append(("untyped", type(e).__name__))

        alice.start_flow(CatchTyped(stx))
        net.run_network()
        assert caught and caught[0][0] == "typed", caught
    finally:
        net.stop_nodes()
