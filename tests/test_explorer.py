"""Explorer tests: the dashboard aggregates every RPC feed of a live node.

Mirrors the reference's explorer data tier (reference: tools/explorer/...,
client/.../model/NodeMonitorModel.kt, ContractStateModel.kt) — GUI shell
replaced by an HTTP dashboard, same RPC-fed content.
"""

import json
import threading
import urllib.request

import pytest

from corda_tpu.finance import Amount
from corda_tpu.finance.cash import Cash
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.node.rpc import RpcClient
from corda_tpu.tools.explorer import ExplorerServer, cash_balances, render_value

RPC_USERS = ({"username": "ops", "password": "pw", "permissions": ["ALL"]},)


@pytest.fixture()
def live_node(tmp_path):
    node = Node(NodeConfig(
        name="Exp", base_dir=tmp_path / "Exp",
        network_map=tmp_path / "netmap.json",
        rpc_users=RPC_USERS)).start()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            node.run_once(timeout=0.01)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    try:
        yield node
    finally:
        stop.set()
        pumper.join(timeout=2)
        node.stop()


def get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def self_issue(node, quantity=5000):
    builder = Cash.generate_issue(
        Amount(quantity, "USD"), node.identity.ref(b"\x01"),
        node.identity.owning_key, node.identity)
    builder.sign_with(node.key)
    stx = builder.to_signed_transaction()
    node.services.record_transactions([stx])
    return stx


def test_render_value_handles_ledger_types(live_node):
    stx = self_issue(live_node)
    rendered = render_value(stx)
    assert rendered["_type"] == "SignedTransaction"
    flat = json.dumps(rendered)
    assert "CashState" in flat and "USD" in flat


def test_dashboard_aggregates_node_state(live_node):
    self_issue(live_node, 5000)
    self_issue(live_node, 1250)
    client = RpcClient(live_node.messaging.my_address, "ops", "pw")
    server = ExplorerServer(client)
    try:
        host, port = server.address
        status, ctype, body = get(f"http://{host}:{port}/")
        assert status == 200 and "text/html" in ctype
        assert b"corda_tpu explorer" in body

        status, ctype, body = get(f"http://{host}:{port}/api/dashboard")
        assert status == 200 and "application/json" in ctype
        d = json.loads(body)
        assert d["identity"] == "Exp"
        assert d["balances"] == {"USD": 6250}
        assert len(d["vault"]) == 2
        assert len(d["transactions"]) == 2
        assert "flows_started" in d["metrics"] or d["metrics"] is not None
        # second poll keeps working (cursor advances without error)
        status, _, body2 = get(f"http://{host}:{port}/api/dashboard")
        assert status == 200
        assert json.loads(body2)["balances"] == {"USD": 6250}

        status, _, _ = get(f"http://{host}:{port}/api/dashboard")
        assert status == 200
    finally:
        server.stop()
        client.close()


def test_cash_balances_ignores_foreign_states():
    assert cash_balances([]) == {}


def test_demo_traffic_populates_vault(live_node):
    """The explorer's simulation mode (reference: explorer Main.kt -S +
    client/mock EventGenerator): generated issues/moves appear in the vault
    and therefore on the dashboard."""
    import time

    from corda_tpu.finance import CashState
    from corda_tpu.tools.explorer import DemoTraffic

    traffic = DemoTraffic(live_node, period=0.01, seed=7)
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            states = live_node.services.vault_service.unconsumed_states(
                CashState)
            txs = len(live_node.services.storage_service
                      .validated_transactions)
            if states and txs >= 5:
                break
            time.sleep(0.05)
        assert states, "demo traffic never issued cash"
        assert txs >= 5, "demo traffic stalled"
        assert cash_balances(
            live_node.services.vault_service.current_vault.states)
    finally:
        traffic.stop()


def test_dashboard_joins_tx_provenance(tmp_path):
    """The tx view attributes ledger activity to the flow run that
    produced it (reference: the explorer's GatheredTransactionData joins
    flows to txs through StateMachineRecordedTransactionMappingStorage)."""
    import time

    import corda_tpu.tools.demo_cordapp  # noqa: F401  (registers the flow)
    from corda_tpu.tools.explorer import ExplorerModel

    node = Node(NodeConfig(
        name="ProvExp", base_dir=tmp_path / "ProvExp",
        network_map=tmp_path / "netmap.json", notary="simple",
        rpc_users=RPC_USERS)).start()
    stop = threading.Event()
    pumper = threading.Thread(
        target=lambda: [node.run_once(timeout=0.01)
                        for _ in iter(stop.is_set, True)], daemon=True)
    pumper.start()
    client = RpcClient(node.messaging.my_address, "ops", "pw")
    try:
        handle = client.call(
            "start_flow_dynamic", "IssueAndNotariseFlow", (7,))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            done, _ = client.call("flow_result", handle.run_id)
            if done:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("demo flow did not finish")
        model = ExplorerModel(client)
        dash = model.gather()
        run_short = handle.run_id.hex()[:8]
        attributed = [tx for tx, runs in dash["tx_provenance"].items()
                      if run_short in runs]
        assert len(attributed) == 2, dash["tx_provenance"]
    finally:
        client.close()
        stop.set()
        pumper.join(timeout=2)
        node.stop()
