"""Fault-injection engine contract (corda_tpu.testing.faults).

Tier-1 smoke tier for the chaos harness: the engine is deterministic under
a seed, each injection point actually fires through its wired hook, and a
disarmed process pays no semantic change. The end-to-end chaos soaks live
in test_chaos_recovery.py.
"""

import threading

import pytest

from corda_tpu.testing import faults
from corda_tpu.testing.faults import FaultPlan, FaultRule, PartitionSpec


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process disarmed — the plan is module-global
    and a leak would inject faults into unrelated tests."""
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# Engine semantics
# ---------------------------------------------------------------------------


def _schedule(plan: FaultPlan, point: str, n: int) -> list:
    return [plan.fire(point) for _ in range(n)]


def test_same_seed_same_schedule():
    mk = lambda: FaultPlan(42, [  # noqa: E731
        FaultRule("transport.send", "drop", p=0.3),
        FaultRule("raft.append", "delay", p=0.5, delay_s=0.01),
    ])
    a, b = mk(), mk()
    assert _schedule(a, "transport.send", 50) == \
        _schedule(b, "transport.send", 50)
    assert _schedule(a, "raft.append", 50) == _schedule(b, "raft.append", 50)
    assert a.injected() == b.injected()
    assert any(v for v in a.injected().values()), "p=0.3/0.5 never fired"


def test_different_seed_different_schedule():
    a = FaultPlan(1, [FaultRule("transport.send", "drop", p=0.5)])
    b = FaultPlan(2, [FaultRule("transport.send", "drop", p=0.5)])
    assert _schedule(a, "transport.send", 100) != \
        _schedule(b, "transport.send", 100)


def test_node_filter_does_not_perturb_schedule():
    """Dropping another node's rules must not shift the surviving rules'
    RNG streams (rules are seeded by original index, not surviving index)."""
    rules = lambda: [  # noqa: E731
        FaultRule("raft.fsync", "stall", p=0.4, node="Raft0"),
        FaultRule("transport.send", "drop", p=0.4, node="Raft1"),
    ]
    both = FaultPlan(9, rules())
    only1 = FaultPlan(9, rules(), node_name="Raft1")
    assert len(only1.rules) == 1  # Raft0's fsync rule filtered out
    assert _schedule(both, "transport.send", 40) == \
        _schedule(only1, "transport.send", 40)


def test_after_and_max_fires_bound_the_rule():
    plan = FaultPlan(0, [
        FaultRule("transport.recv", "drop", after=3, max_fires=2)])
    acts = _schedule(plan, "transport.recv", 10)
    assert acts == [None, None, None, ("drop", 0.0), ("drop", 0.0),
                    None, None, None, None, None]
    assert plan.injected() == {"transport.recv:drop": 2}
    assert plan.event_counts() == {"transport.recv": 10}


def test_unknown_point_rejected():
    with pytest.raises(ValueError):
        FaultPlan(0, [FaultRule("transport.teleport", "drop")])


def test_disarmed_module_hooks_are_noops():
    assert faults.ACTIVE is None
    assert faults.fire("transport.send") is None
    assert faults.injected() == {}
    faults.fire_fsync("raft.fsync")  # must not raise


def test_fire_fsync_fail_raises_and_stall_sleeps():
    faults.arm(FaultPlan(0, [FaultRule("raft.fsync", "fail")]))
    with pytest.raises(OSError):
        faults.fire_fsync("raft.fsync")
    faults.arm(FaultPlan(0, [
        FaultRule("checkpoint.write", "stall", delay_s=0.001)]))
    faults.fire_fsync("checkpoint.write")  # stall returns after sleeping
    assert faults.injected() == {"checkpoint.write:stall": 1}


def test_plan_from_toml():
    plan = faults.plan_from_toml(
        """
        seed = 21

        [[rule]]
        point = "transport.send"
        action = "drop"
        p = 0.25
        max_fires = 10

        [[rule]]
        point = "verify.device"
        action = "fail"
        node = "Raft2"
        """,
        node_name="Raft0")
    assert plan.seed == 21
    assert len(plan.rules) == 1  # Raft2's rule filtered for Raft0
    r = plan.rules[0]
    assert (r.point, r.action, r.p, r.max_fires) == \
        ("transport.send", "drop", 0.25, 10)


def test_builtin_plans():
    for name in ("lossy", "slow-disk", "flaky-device"):
        plan = faults.builtin_plan(name)
        assert plan.rules
    with pytest.raises(ValueError):
        faults.builtin_plan("nope")


def test_arm_from_env(tmp_path, monkeypatch):
    path = tmp_path / "plan.toml"
    path.write_text('seed = 3\n[[rule]]\npoint = "raft.append"\n'
                    'action = "drop"\n')
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    assert faults.arm_from_env("N") is None
    monkeypatch.setenv(faults.PLAN_ENV, str(path))
    plan = faults.arm_from_env("N")
    assert plan is faults.ACTIVE
    assert plan.rules[0].point == "raft.append"


def test_fire_is_thread_safe():
    plan = faults.arm(FaultPlan(0, [
        FaultRule("transport.send", "drop", p=0.5)]))
    errs = []

    def worker():
        try:
            for _ in range(500):
                plan.fire("transport.send")
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert plan.event_counts()["transport.send"] == 2000


# ---------------------------------------------------------------------------
# Wired hooks (cheap in-process paths)
# ---------------------------------------------------------------------------


def _inmem_pair():
    from corda_tpu.node.messaging.inmem import InMemoryMessagingNetwork

    net = InMemoryMessagingNetwork()
    a = net.create_node_messaging("A")
    b = net.create_node_messaging("B")
    got = []
    b.add_message_handler("t", callback=lambda msg: got.append(msg.data))
    return net, a, b, got


def test_inmem_send_drop_and_duplicate():
    from corda_tpu.node.messaging.api import TopicSession

    net, a, b, got = _inmem_pair()
    faults.arm(FaultPlan(0, [FaultRule("transport.send", "drop",
                                       max_fires=1)]))
    a.send(TopicSession("t", 0), b"m0", b.my_address)
    a.send(TopicSession("t", 0), b"m1", b.my_address)
    net.run()
    assert got == [b"m1"]
    assert faults.injected() == {"transport.send:drop": 1}

    got.clear()
    faults.arm(FaultPlan(0, [FaultRule("transport.send", "duplicate",
                                       max_fires=1)]))
    a.send(TopicSession("t", 0), b"m2", b.my_address)
    net.run()
    # The duplicate reaches the endpoint twice; at-least-once dedupe by
    # unique_id absorbs the second copy — exactly-once delivery holds.
    assert got == [b"m2"]
    assert faults.injected() == {"transport.send:duplicate": 1}


def test_inmem_recv_drop():
    from corda_tpu.node.messaging.api import TopicSession

    net, a, b, got = _inmem_pair()
    faults.arm(FaultPlan(0, [FaultRule("transport.recv", "drop",
                                       max_fires=1)]))
    a.send(TopicSession("t", 0), b"m0", b.my_address)
    a.send(TopicSession("t", 0), b"m1", b.my_address)
    net.run()
    assert got == [b"m1"]
    assert faults.injected() == {"transport.recv:drop": 1}


def test_async_verify_device_fault_crosses_to_handle():
    """A verify.device 'fail' surfaces as handle.error after drain — the
    seam the SMM degrade path consumes."""
    from corda_tpu.crypto.async_verify import AsyncVerifyService
    from corda_tpu.crypto.provider import CpuVerifier, VerifyJob

    svc = AsyncVerifyService(CpuVerifier(), depth=2, adaptive=False)
    faults.arm(FaultPlan(0, [FaultRule("verify.device", "fail",
                                       max_fires=1)]))
    jobs = [VerifyJob(bytes(32), bytes(32), bytes(64))]
    svc.submit(jobs, context="c1")
    svc.submit(jobs, context="c2")
    done = []
    deadline = 100
    while len(done) < 2 and deadline:
        done.extend(svc.drain())
        deadline -= 1
        if len(done) < 2:
            import time

            time.sleep(0.01)
    assert len(done) == 2
    by_ctx = {h.context: h for h in done}
    assert isinstance(by_ctx["c1"].error, RuntimeError)
    assert by_ctx["c2"].error is None and by_ctx["c2"].ok is not None
    assert svc.close()


# ---------------------------------------------------------------------------
# Partition engine (round 20): event-counted cuts, no timing dependence
# ---------------------------------------------------------------------------


def _drops(plan, frames):
    return [plan.fire_partition(s, d) for s, d in frames]


def test_partition_schedule_is_deterministic():
    mk = lambda: FaultPlan(5, [], partitions=[  # noqa: E731
        PartitionSpec("split", after=3, duration=6)])
    frames = [("A", "B"), ("B", "A"), ("A", "C")] * 6
    a, b = mk(), mk()
    a.bind_partition_nodes(["A", "B", "C"])
    b.bind_partition_nodes(["A", "B", "C"])
    assert _drops(a, frames) == _drops(b, frames)
    assert a.injected() == b.injected()
    assert a.injected().get("transport.partition:drop"), \
        "the cut never dropped a frame"


def test_partition_split_cuts_both_directions_then_heals():
    plan = FaultPlan(0, [], partitions=[
        PartitionSpec("split", a=("A",), b=("B",))])
    assert plan.fire_partition("A", "B") is True
    assert plan.fire_partition("B", "A") is True
    assert plan.fire_partition("A", "C") is False  # C is on no side
    assert plan.injected()["transport.partition:cut"] == 1  # one edge
    assert plan.injected()["transport.partition:drop"] == 2
    plan.heal_partitions()
    assert plan.fire_partition("A", "B") is False
    assert plan.partitioned("A", "B") is False


def test_partition_asym_cuts_one_way_only():
    plan = FaultPlan(0, [], partitions=[
        PartitionSpec("asym", a=("A",), b=("B",))])
    assert plan.fire_partition("A", "B") is True   # egress cut
    assert plan.fire_partition("B", "A") is False  # half-open: can hear


def test_partition_flap_toggles_by_events():
    plan = FaultPlan(0, [], partitions=[
        PartitionSpec("flap", a=("A",), b=("B",), period=2)])
    # (since-1)//period alternates every `period` events: on,on,off,off,...
    assert _drops(plan, [("A", "B")] * 8) == \
        [True, True, False, False, True, True, False, False]


def test_partition_flap_seeded_period_is_deterministic():
    a = FaultPlan(11, [], partitions=[PartitionSpec("flap")])
    b = FaultPlan(11, [], partitions=[PartitionSpec("flap")])
    c = FaultPlan(12, [], partitions=[PartitionSpec("flap")])
    assert a.partitions[0].period == b.partitions[0].period
    assert 40 <= a.partitions[0].period < 160
    assert (a.partitions[0].period != c.partitions[0].period
            or a.seed != c.seed)


def test_bind_partition_nodes_first_bound_is_minority():
    plan = FaultPlan(0, [], partitions=[PartitionSpec("split"),
                                        PartitionSpec("asym")])
    plan.bind_partition_nodes(["L", "F1", "F2"])
    split, asym = plan.partitions
    assert split.a == ("L",) and split.b == ("F1", "F2")
    assert asym.a == ("L",) and asym.b == ("F1", "F2")
    # Explicit sides are never rebound.
    plan2 = FaultPlan(0, [], partitions=[
        PartitionSpec("split", a=("X",), b=("Y",))])
    plan2.bind_partition_nodes(["L", "F1"])
    assert plan2.partitions[0].a == ("X",)


def test_partitioned_query_never_advances_the_schedule():
    plan = FaultPlan(0, [], partitions=[
        PartitionSpec("split", a=("A",), b=("B",), after=2)])
    for _ in range(10):
        assert plan.partitioned("A", "B") is False  # cut not armed yet
    assert plan.event_counts().get("transport.partition") is None
    plan.fire_partition("A", "B")
    plan.fire_partition("A", "B")
    plan.fire_partition("A", "B")  # event 3 > after=2: armed
    assert plan.partitioned("A", "B") is True
    assert plan.event_counts()["transport.partition"] == 3


def test_partition_unknown_kind_rejected():
    with pytest.raises(ValueError):
        FaultPlan(0, [], partitions=[PartitionSpec("wormhole")])


def test_partition_plan_from_toml():
    plan = faults.plan_from_toml(
        """
        seed = 3

        [[rule]]
        point = "transport.send"
        action = "drop"
        p = 0.05

        [[partition]]
        kind = "split"
        after = 100
        duration = 500

        [[partition]]
        kind = "asym"
        a = ["RaftA:1"]
        b = ["RaftB:1", "RaftC:1"]
        """)
    assert len(plan.rules) == 1  # rules and partitions compose in one plan
    assert len(plan.partitions) == 2
    split, asym = plan.partitions
    assert (split.kind, split.after, split.duration) == ("split", 100, 500)
    assert asym.a == ("RaftA:1",) and len(asym.b) == 2


def test_builtin_partition_plans():
    for name in ("split-brain", "asym", "flap"):
        plan = faults.builtin_plan(name)
        assert plan.partitions
        # The CLI pass-through prefix resolves to the same plan.
        assert faults.builtin_plan(f"partition.{name}").partitions
    # split-brain composes the cut with a lossy rule in ONE plan.
    assert faults.builtin_plan("split-brain").rules


def test_inmem_partition_cut_drops_then_heal_delivers():
    from corda_tpu.node.messaging.api import TopicSession

    net, a, b, got = _inmem_pair()
    plan = faults.arm(FaultPlan(0, [], partitions=[PartitionSpec("split")]))
    plan.bind_partition_nodes([a.my_address, b.my_address])
    a.send(TopicSession("t", 0), b"cut", b.my_address)
    net.run()
    assert got == []  # the frame died at the send-side hook
    assert faults.injected()["transport.partition:drop"] >= 1
    faults.heal_partitions()
    a.send(TopicSession("t", 0), b"healed", b.my_address)
    net.run()
    assert got == [b"healed"]


def test_inmem_flap_soak_delivers_exactly_once():
    """At-least-once retries through a flapping cut: every payload lands,
    and redelivered copies (same unique_id) are absorbed by dedupe —
    exactly-once processing holds through the rejoin storm."""
    from corda_tpu.node.messaging.api import Message, TopicSession
    from corda_tpu.node.messaging.inmem import fresh_message_id

    net, a, b, got = _inmem_pair()
    plan = faults.arm(FaultPlan(0, [], partitions=[
        PartitionSpec("flap", period=3)]))
    plan.bind_partition_nodes([a.my_address, b.my_address])
    payloads = [b"m%d" % i for i in range(12)]
    sent = []
    for data in payloads:
        msg = Message(TopicSession("t", 0), data, fresh_message_id(),
                      sender=a.my_address)
        sent.append((data, msg))
        for _ in range(50):  # the retry loop is the at-least-once layer
            net._transmit(a.my_address, b.my_address, msg)
            net.run()
            if data in got:
                break
        else:  # pragma: no cover - failure path
            raise AssertionError(f"{data!r} never crossed the flap")
        # Resend every delivered frame once more (the at-least-once layer
        # cannot know the ack raced the cut) — dedupe must absorb any
        # copy the flap lets through.
        net._transmit(a.my_address, b.my_address, msg)
        net.run()
    # Heal and redeliver everything once more: every copy now ARRIVES,
    # and every one must be absorbed by unique_id dedupe.
    faults.heal_partitions()
    for data, msg in sent:
        net._transmit(a.my_address, b.my_address, msg)
    net.run()
    assert got == payloads  # each exactly once, in order
    assert b._redeliveries >= len(payloads)  # duplicates absorbed
    assert faults.injected()["transport.partition:drop"] > 0


def test_tcp_asym_cut_parks_bridge_then_heal_redelivers():
    """One-way TCP cut: the victim's egress frames park in the durable
    outbox (the bridge waits on `partitioned` instead of spin-resending
    into the void); the reverse direction still delivers. Heal wakes the
    bridge and the parked frame redelivers — nothing is lost."""
    import time

    from corda_tpu.node.messaging.api import TopicSession
    from corda_tpu.node.messaging.tcp import TcpMessaging

    a = TcpMessaging("127.0.0.1", 0).start()
    b = TcpMessaging("127.0.0.1", 0).start()
    try:
        got_a, got_b = [], []
        a.add_message_handler("t", callback=lambda m: got_a.append(m.data))
        b.add_message_handler("t", callback=lambda m: got_b.append(m.data))
        faults.arm(FaultPlan(0, [], partitions=[
            PartitionSpec("asym", a=(str(a.my_address),),
                          b=(str(b.my_address),))]))
        a.send(TopicSession("t", 0), b"a->b", b.my_address)  # cut egress
        b.send(TopicSession("t", 0), b"b->a", a.my_address)  # half-open
        deadline = time.monotonic() + 10
        while not got_a and time.monotonic() < deadline:
            a.pump(timeout=0.02)
            b.pump(timeout=0.02)
        assert got_a == [b"b->a"]
        assert got_b == []  # the cut held a's egress
        assert a.outbox_backlog(b.my_address) == 1  # durable row parked
        faults.heal_partitions()
        # A held cut parks frames in the outbox; the NEXT send after heal
        # wakes the bridge and the whole backlog replays in seq order
        # (in a live cluster raft heartbeats are that next send).
        a.send(TopicSession("t", 0), b"a->b2", b.my_address)
        deadline = time.monotonic() + 10
        while len(got_b) < 2 and time.monotonic() < deadline:
            a.pump(timeout=0.02)
            b.pump(timeout=0.02)
        assert got_b == [b"a->b", b"a->b2"]  # parked frame redelivered
    finally:
        a.stop()
        b.stop()
