"""Fault-injection engine contract (corda_tpu.testing.faults).

Tier-1 smoke tier for the chaos harness: the engine is deterministic under
a seed, each injection point actually fires through its wired hook, and a
disarmed process pays no semantic change. The end-to-end chaos soaks live
in test_chaos_recovery.py.
"""

import threading

import pytest

from corda_tpu.testing import faults
from corda_tpu.testing.faults import FaultPlan, FaultRule


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process disarmed — the plan is module-global
    and a leak would inject faults into unrelated tests."""
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# Engine semantics
# ---------------------------------------------------------------------------


def _schedule(plan: FaultPlan, point: str, n: int) -> list:
    return [plan.fire(point) for _ in range(n)]


def test_same_seed_same_schedule():
    mk = lambda: FaultPlan(42, [  # noqa: E731
        FaultRule("transport.send", "drop", p=0.3),
        FaultRule("raft.append", "delay", p=0.5, delay_s=0.01),
    ])
    a, b = mk(), mk()
    assert _schedule(a, "transport.send", 50) == \
        _schedule(b, "transport.send", 50)
    assert _schedule(a, "raft.append", 50) == _schedule(b, "raft.append", 50)
    assert a.injected() == b.injected()
    assert any(v for v in a.injected().values()), "p=0.3/0.5 never fired"


def test_different_seed_different_schedule():
    a = FaultPlan(1, [FaultRule("transport.send", "drop", p=0.5)])
    b = FaultPlan(2, [FaultRule("transport.send", "drop", p=0.5)])
    assert _schedule(a, "transport.send", 100) != \
        _schedule(b, "transport.send", 100)


def test_node_filter_does_not_perturb_schedule():
    """Dropping another node's rules must not shift the surviving rules'
    RNG streams (rules are seeded by original index, not surviving index)."""
    rules = lambda: [  # noqa: E731
        FaultRule("raft.fsync", "stall", p=0.4, node="Raft0"),
        FaultRule("transport.send", "drop", p=0.4, node="Raft1"),
    ]
    both = FaultPlan(9, rules())
    only1 = FaultPlan(9, rules(), node_name="Raft1")
    assert len(only1.rules) == 1  # Raft0's fsync rule filtered out
    assert _schedule(both, "transport.send", 40) == \
        _schedule(only1, "transport.send", 40)


def test_after_and_max_fires_bound_the_rule():
    plan = FaultPlan(0, [
        FaultRule("transport.recv", "drop", after=3, max_fires=2)])
    acts = _schedule(plan, "transport.recv", 10)
    assert acts == [None, None, None, ("drop", 0.0), ("drop", 0.0),
                    None, None, None, None, None]
    assert plan.injected() == {"transport.recv:drop": 2}
    assert plan.event_counts() == {"transport.recv": 10}


def test_unknown_point_rejected():
    with pytest.raises(ValueError):
        FaultPlan(0, [FaultRule("transport.teleport", "drop")])


def test_disarmed_module_hooks_are_noops():
    assert faults.ACTIVE is None
    assert faults.fire("transport.send") is None
    assert faults.injected() == {}
    faults.fire_fsync("raft.fsync")  # must not raise


def test_fire_fsync_fail_raises_and_stall_sleeps():
    faults.arm(FaultPlan(0, [FaultRule("raft.fsync", "fail")]))
    with pytest.raises(OSError):
        faults.fire_fsync("raft.fsync")
    faults.arm(FaultPlan(0, [
        FaultRule("checkpoint.write", "stall", delay_s=0.001)]))
    faults.fire_fsync("checkpoint.write")  # stall returns after sleeping
    assert faults.injected() == {"checkpoint.write:stall": 1}


def test_plan_from_toml():
    plan = faults.plan_from_toml(
        """
        seed = 21

        [[rule]]
        point = "transport.send"
        action = "drop"
        p = 0.25
        max_fires = 10

        [[rule]]
        point = "verify.device"
        action = "fail"
        node = "Raft2"
        """,
        node_name="Raft0")
    assert plan.seed == 21
    assert len(plan.rules) == 1  # Raft2's rule filtered for Raft0
    r = plan.rules[0]
    assert (r.point, r.action, r.p, r.max_fires) == \
        ("transport.send", "drop", 0.25, 10)


def test_builtin_plans():
    for name in ("lossy", "slow-disk", "flaky-device"):
        plan = faults.builtin_plan(name)
        assert plan.rules
    with pytest.raises(ValueError):
        faults.builtin_plan("nope")


def test_arm_from_env(tmp_path, monkeypatch):
    path = tmp_path / "plan.toml"
    path.write_text('seed = 3\n[[rule]]\npoint = "raft.append"\n'
                    'action = "drop"\n')
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    assert faults.arm_from_env("N") is None
    monkeypatch.setenv(faults.PLAN_ENV, str(path))
    plan = faults.arm_from_env("N")
    assert plan is faults.ACTIVE
    assert plan.rules[0].point == "raft.append"


def test_fire_is_thread_safe():
    plan = faults.arm(FaultPlan(0, [
        FaultRule("transport.send", "drop", p=0.5)]))
    errs = []

    def worker():
        try:
            for _ in range(500):
                plan.fire("transport.send")
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert plan.event_counts()["transport.send"] == 2000


# ---------------------------------------------------------------------------
# Wired hooks (cheap in-process paths)
# ---------------------------------------------------------------------------


def _inmem_pair():
    from corda_tpu.node.messaging.inmem import InMemoryMessagingNetwork

    net = InMemoryMessagingNetwork()
    a = net.create_node_messaging("A")
    b = net.create_node_messaging("B")
    got = []
    b.add_message_handler("t", callback=lambda msg: got.append(msg.data))
    return net, a, b, got


def test_inmem_send_drop_and_duplicate():
    from corda_tpu.node.messaging.api import TopicSession

    net, a, b, got = _inmem_pair()
    faults.arm(FaultPlan(0, [FaultRule("transport.send", "drop",
                                       max_fires=1)]))
    a.send(TopicSession("t", 0), b"m0", b.my_address)
    a.send(TopicSession("t", 0), b"m1", b.my_address)
    net.run()
    assert got == [b"m1"]
    assert faults.injected() == {"transport.send:drop": 1}

    got.clear()
    faults.arm(FaultPlan(0, [FaultRule("transport.send", "duplicate",
                                       max_fires=1)]))
    a.send(TopicSession("t", 0), b"m2", b.my_address)
    net.run()
    # The duplicate reaches the endpoint twice; at-least-once dedupe by
    # unique_id absorbs the second copy — exactly-once delivery holds.
    assert got == [b"m2"]
    assert faults.injected() == {"transport.send:duplicate": 1}


def test_inmem_recv_drop():
    from corda_tpu.node.messaging.api import TopicSession

    net, a, b, got = _inmem_pair()
    faults.arm(FaultPlan(0, [FaultRule("transport.recv", "drop",
                                       max_fires=1)]))
    a.send(TopicSession("t", 0), b"m0", b.my_address)
    a.send(TopicSession("t", 0), b"m1", b.my_address)
    net.run()
    assert got == [b"m1"]
    assert faults.injected() == {"transport.recv:drop": 1}


def test_async_verify_device_fault_crosses_to_handle():
    """A verify.device 'fail' surfaces as handle.error after drain — the
    seam the SMM degrade path consumes."""
    from corda_tpu.crypto.async_verify import AsyncVerifyService
    from corda_tpu.crypto.provider import CpuVerifier, VerifyJob

    svc = AsyncVerifyService(CpuVerifier(), depth=2, adaptive=False)
    faults.arm(FaultPlan(0, [FaultRule("verify.device", "fail",
                                       max_fires=1)]))
    jobs = [VerifyJob(bytes(32), bytes(32), bytes(64))]
    svc.submit(jobs, context="c1")
    svc.submit(jobs, context="c2")
    done = []
    deadline = 100
    while len(done) < 2 and deadline:
        done.extend(svc.drain())
        deadline -= 1
        if len(done) < 2:
            import time

            time.sleep(0.01)
    assert len(done) == 2
    by_ctx = {h.context: h for h in done}
    assert isinstance(by_ctx["c1"].error, RuntimeError)
    assert by_ctx["c2"].error is None and by_ctx["c2"].ok is not None
    assert svc.close()
