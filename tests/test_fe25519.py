"""fe25519 limb arithmetic vs Python big-int ground truth."""

import numpy as np
import pytest

import jax.numpy as jnp

from corda_tpu.ops import fe25519 as fe

P = fe.P
rng = np.random.default_rng(1234)


def rand_ints(n):
    return [int.from_bytes(rng.bytes(33), "little") % (1 << 260) for _ in range(n)]


def batch_of(vals):
    """list of python ints -> (20, N) device array."""
    return jnp.asarray(np.stack([fe.limbs_of_int(v) for v in vals], axis=1))


def as_ints(limbs):
    arr = np.asarray(limbs)
    return [fe.int_of_limbs(arr[:, j]) for j in range(arr.shape[1])]


EDGE = [0, 1, 2, 19, P - 1, P, P + 1, 2 * P, (1 << 255) - 1, (1 << 260) - 1,
        fe.FOLD, P - 19]


def test_roundtrip_limbs():
    vals = EDGE + rand_ints(20)
    assert as_ints(batch_of(vals)) == vals


@pytest.mark.parametrize("op,pyop", [
    (fe.add, lambda a, b: (a + b) % P),
    (fe.sub, lambda a, b: (a - b) % P),
    (fe.mul, lambda a, b: (a * b) % P),
])
def test_binary_ops(op, pyop):
    avals = EDGE + rand_ints(20)
    bvals = rand_ints(len(EDGE)) + EDGE + rand_ints(8)
    a, b = batch_of(avals), batch_of(bvals[: len(avals)])
    got = as_ints(op(a, b))
    arr = np.asarray(op(a, b))
    # Lazy contract: congruent mod p, limbs within the mul-input bound.
    assert abs(arr).max() <= 10_000
    for g, x, y in zip(got, avals, bvals):
        assert g % P == pyop(x, y), (x, y)


def test_lazy_ops_compose_within_mul_bound():
    # add/sub/mul outputs must be directly usable as mul inputs: chain a few
    # and compare against big-int ground truth.
    vals = rand_ints(6)
    a, b = batch_of(vals[:3]), batch_of(vals[3:])
    out = fe.mul(fe.add(a, b), fe.sub(a, b))          # (a+b)(a-b)
    out = fe.mul(out, fe.mul_small(fe.neg(a), 2))      # * (-2a)
    got = as_ints(fe.freeze(out))
    for g, x, y in zip(got, vals[:3], vals[3:]):
        assert g == (x + y) * (x - y) * (-2 * x) % P


def test_neg_signed():
    vals = EDGE + rand_ints(10)
    a = batch_of(vals)
    got = as_ints(fe.neg(a))
    for g, x in zip(got, vals):
        assert g % P == (-x) % P


def test_freeze_canonical():
    vals = EDGE + rand_ints(20)
    frozen = as_ints(fe.freeze(batch_of(vals)))
    for f, x in zip(frozen, vals):
        assert f == x % P


def test_inv():
    vals = [1, 2, P - 1] + rand_ints(5)
    a = batch_of(vals)
    got = as_ints(fe.freeze(fe.inv(a)))
    for g, x in zip(got, vals):
        assert g == pow(x, P - 2, P)


def test_inv_zero_is_zero():
    assert as_ints(fe.freeze(fe.inv(batch_of([0]))))[0] == 0


def test_pow_p58():
    vals = rand_ints(5)
    got = as_ints(fe.freeze(fe.pow_p58(batch_of(vals))))
    for g, x in zip(got, vals):
        assert g == pow(x, (P - 5) // 8, P)


def test_is_zero_eq():
    a = batch_of([0, P, 5, 2 * P])
    assert np.asarray(fe.is_zero(a)).tolist() == [True, True, False, True]
    b = batch_of([P, 0, 5 + P, 7])
    assert np.asarray(fe.eq(a, b)).tolist() == [True, True, True, False]


def test_pack_le_bytes():
    raw = rng.integers(0, 256, (16, 32), dtype=np.uint8)
    limbs, sign = fe.pack_le_bytes(raw)
    for j in range(16):
        n = int.from_bytes(raw[j].tobytes(), "little")
        assert fe.int_of_limbs(limbs[:, j]) == n & ((1 << 255) - 1)
        assert sign[j] == n >> 255


def test_scalar_bits_msb():
    raw = rng.integers(0, 256, (4, 32), dtype=np.uint8)
    bits = fe.scalar_bits_msb(raw)
    for j in range(4):
        n = int.from_bytes(raw[j].tobytes(), "little")
        got = 0
        for i in range(256):
            got = (got << 1) | int(bits[i, j])
        assert got == n


def test_normalize_exact_weak_reduction():
    # normalize must take lazy (signed, out-of-range) limbs to canonical
    # [0, 2^13) limbs with value < 2^260, preserving the residue.
    rng2 = np.random.default_rng(7)
    raw = rng2.integers(-10_000, 10_000, size=(20, 5), dtype=np.int64).astype(np.int32)
    want = [
        sum(int(raw[i, j]) << (fe.RADIX * i) for i in range(20)) % fe.P
        for j in range(5)
    ]
    got = np.asarray(fe.normalize(jnp.asarray(raw)))
    assert got.min() >= 0 and got.max() < 1 << fe.RADIX
    for j in range(5):
        assert fe.int_of_limbs(got[:, j]) % fe.P == want[j]
