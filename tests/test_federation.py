"""Federated verify plane (crypto/federation.py): deterministic routing,
hedged re-dispatch, per-host quarantine -> re-probe -> re-admit, the
whole-tier degrade when every host is lost, the per-endpoint server-stats
cache, and the federation-off bit-identity of the node's verifier
selection. Everything here drives the router through its test seams
(``pick_host`` directly; ``_channel_verify`` stubbed) — no sockets, no
sidecar processes: the live wire path is tier-2 (bench multihost_scaling
+ the driver smoke)."""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

from corda_tpu.crypto.federation import (BULK_STICK_CAP_SIGS,
                                         FederatedVerifier)
from corda_tpu.crypto.provider import VerifyJob
from corda_tpu.crypto.sidecar import LANE_CODE_BULK, LANE_CODE_INTERACTIVE
from corda_tpu.node.verify_client import SidecarError


def _fed(n_hosts=3, **kw):
    kw.setdefault("device_min_sigs", 0)
    return FederatedVerifier([f"/nonexistent/host{i}.sock"
                              for i in range(n_hosts)], **kw)


def _jobs(n=4):
    # Garbage jobs: every tier (remote stub, local host oracle) rejects
    # them identically, which is exactly what the fallback tests need.
    return [VerifyJob(b"\x01" * 32, b"m%d" % i, b"\x02" * 64)
            for i in range(n)]


# -- routing policy ----------------------------------------------------------


def test_interactive_routes_to_least_depth_with_index_tiebreak():
    fed = _fed(3)
    fed.channels[0].in_flight_sigs = 100
    fed.channels[1].in_flight_sigs = 10
    fed.channels[2].in_flight_sigs = 10
    # Least depth wins; the 1-vs-2 tie breaks on the lower index.
    assert fed.pick_host(8, LANE_CODE_INTERACTIVE) is fed.channels[1]
    # Unlabelled traffic ranks exactly like interactive.
    assert fed.pick_host(8, None) is fed.channels[1]
    fed.channels[1].in_flight_sigs = 200
    assert fed.pick_host(8, None) is fed.channels[2]


def test_bulk_sticks_to_busiest_open_window_under_cap():
    fed = _fed(3)
    fed.channels[0].in_flight_sigs = 50
    fed.channels[1].in_flight_sigs = 300   # busiest open window
    fed.channels[2].in_flight_sigs = 0     # idle
    # Bulk coalesce-sticks to the busiest window instead of opening a
    # fresh one on the idle host (which interactive would pick).
    assert fed.pick_host(8, LANE_CODE_BULK) is fed.channels[1]
    assert fed.pick_host(8, LANE_CODE_INTERACTIVE) is fed.channels[2]
    # Above the stick cap the window is full: bulk spreads like
    # interactive again.
    fed.channels[1].in_flight_sigs = BULK_STICK_CAP_SIGS
    fed.channels[0].in_flight_sigs = BULK_STICK_CAP_SIGS
    assert fed.pick_host(8, LANE_CODE_BULK) is fed.channels[2]


def test_bulk_with_no_open_window_routes_least_depth():
    fed = _fed(2)
    assert fed.pick_host(8, LANE_CODE_BULK) is fed.channels[0]


def test_unhealthy_hosts_are_skipped_and_none_when_all_down():
    fed = _fed(2)
    fed.channels[0].healthy.clear()
    assert fed.pick_host(8, None) is fed.channels[1]
    fed.channels[1].healthy.clear()
    assert fed.pick_host(8, None) is None


# -- hedged re-dispatch ------------------------------------------------------


def test_hedge_fires_exactly_once_and_first_answer_wins(monkeypatch):
    fed = _fed(3, hedge_ms=40.0, reprobe_cooldown_s=60.0)
    jobs = _jobs(4)
    calls = []
    release = threading.Event()

    def channel_verify(channel, jb, hint):
        calls.append(channel.index)
        if channel.index == 0:
            # Slow primary: parks well past the hedge threshold.
            release.wait(5.0)
            return np.ones(len(jb), bool)
        return np.zeros(len(jb), bool)

    monkeypatch.setattr(fed, "_channel_verify", channel_verify)
    out = fed._verify_ed25519_device(jobs)
    release.set()
    # The hedge (host 1: next-ranked healthy, never the primary) answered
    # first and its verdicts won; exactly one hedge was dispatched.
    assert not out.any()
    assert calls == [0, 1]
    assert fed.hedges == 1
    assert fed.channels[0].hedges == 1  # counted against the slow primary
    assert fed.channels[1].hedge_wins == 1
    assert fed.channels[2].dispatches == 0
    # A second, fast batch must not hedge at all.
    calls.clear()
    monkeypatch.setattr(fed, "_channel_verify",
                        lambda c, jb, h: np.zeros(len(jb), bool))
    fed._verify_ed25519_device(jobs)
    assert fed.hedges == 1


def test_slow_primary_verdict_discarded_not_double_applied(monkeypatch):
    fed = _fed(2, hedge_ms=30.0, reprobe_cooldown_s=60.0)
    jobs = _jobs(4)
    primary_done = threading.Event()

    def channel_verify(channel, jb, hint):
        if channel.index == 0:
            time.sleep(0.15)
            primary_done.set()
            return np.ones(len(jb), bool)  # the LOSING verdict
        return np.zeros(len(jb), bool)

    monkeypatch.setattr(fed, "_channel_verify", channel_verify)
    out = fed._verify_ed25519_device(jobs)
    assert not out.any()  # hedge won; the primary's late answer discarded
    assert primary_done.wait(5.0)
    # The loser resolved without corrupting the depth bookkeeping.
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and fed.channels[0].in_flight_sigs:
        time.sleep(0.01)
    assert fed.channels[0].in_flight_sigs == 0
    assert fed.channels[1].in_flight_sigs == 0


# -- failure: quarantine, failover, re-admit ---------------------------------


def test_host_failure_quarantines_and_batch_answers_locally(monkeypatch):
    fed = _fed(2, hedge_ms=5000.0, reprobe_cooldown_s=60.0)
    jobs = _jobs(4)

    def channel_verify(channel, jb, hint):
        if channel.index == 0:
            raise SidecarError("host0 died")
        return np.zeros(len(jb), bool)

    monkeypatch.setattr(fed, "_channel_verify", channel_verify)
    # First batch routes to host 0 (least depth, lowest index), which
    # dies: the batch answers from the oracle-exact LOCAL host tier and
    # host 0 is quarantined — the tier gate stays OPEN (host 1 lives).
    out = fed.verify_batch(jobs)
    assert not np.asarray(out, bool).any()
    assert fed.fallbacks == 1
    assert not fed.channels[0].healthy.is_set()
    assert fed.channels[0].quarantines == 1
    assert fed.host_degraded == 1
    assert fed.device_gate is None or fed.device_gate.is_set()
    # The NEXT batch routes around the quarantined host: remote answer.
    out2 = fed.verify_batch(jobs)
    assert not np.asarray(out2, bool).any()
    assert fed.channels[1].dispatches == 1
    assert fed.device_batches == 1


def test_quarantined_host_reprobes_and_readmits(monkeypatch):
    fed = _fed(2, reprobe_cooldown_s=0.05)
    warm_calls = []

    def warm_flaky():
        warm_calls.append(1)
        if len(warm_calls) < 3:
            raise SidecarError("still down")

    monkeypatch.setattr(fed.channels[0].client, "warm", warm_flaky)
    fed._quarantine(fed.channels[0], SidecarError("boom"))
    assert not fed.channels[0].healthy.is_set()
    deadline = time.monotonic() + 10.0
    while (time.monotonic() < deadline
           and not fed.channels[0].healthy.is_set()):
        time.sleep(0.01)
    # The cooldown ping re-probe kept trying and re-admitted the host.
    assert fed.channels[0].healthy.is_set()
    assert fed.channels[0].readmits == 1
    assert len(warm_calls) >= 3
    # Routing sees it again immediately.
    assert fed.pick_host(8, None) is fed.channels[0]


def test_quarantine_idempotent_while_reprobe_pending(monkeypatch):
    fed = _fed(2, reprobe_cooldown_s=60.0)
    monkeypatch.setattr(
        fed.channels[0].client, "warm",
        lambda: (_ for _ in ()).throw(SidecarError("down")))
    fed._quarantine(fed.channels[0], SidecarError("first"))
    fed._quarantine(fed.channels[0], SidecarError("second"))
    assert fed.channels[0].quarantines == 1  # one quarantine event
    assert fed.channels[0].failures == 2     # ... from two failures
    assert fed.host_degraded == 1


def test_all_hosts_lost_degrades_whole_tier_exact_answer(monkeypatch):
    fed = _fed(2, hedge_ms=5.0, reprobe_cooldown_s=60.0)
    jobs = _jobs(4)
    monkeypatch.setattr(
        fed, "_channel_verify",
        lambda c, jb, h: (_ for _ in ()).throw(SidecarError("dead")))
    for ch in fed.channels:
        monkeypatch.setattr(
            ch.client, "warm",
            lambda: (_ for _ in ()).throw(SidecarError("dead")))
    # A fast-failing primary resolves BEFORE the hedge clock: each batch
    # quarantines one host and answers locally; the gate stays open
    # while any host lives.
    out = fed.verify_batch(jobs)
    assert not np.asarray(out, bool).any()
    assert fed.fallbacks == 1
    assert not fed.channels[0].healthy.is_set()
    assert fed.channels[1].healthy.is_set()
    assert fed.device_gate is None or fed.device_gate.is_set()
    # The second batch kills the survivor: no host left — the WHOLE tier
    # degrades, and the answer is still exact.
    out = fed.verify_batch(jobs)
    assert not np.asarray(out, bool).any()
    assert fed.fallbacks == 2
    assert all(not c.healthy.is_set() for c in fed.channels)
    assert fed.device_gate is not None and not fed.device_gate.is_set()
    assert fed.degraded == 1
    # While degraded, batches route straight to the local host tier.
    fed.verify_batch(jobs)
    assert fed.host_batches == 3


def test_device_method_raises_when_no_host_healthy():
    fed = _fed(2)
    for c in fed.channels:
        c.healthy.clear()
    with pytest.raises(SidecarError):
        fed._verify_ed25519_device(_jobs(2))


# -- stamps ------------------------------------------------------------------


def test_federation_stats_shares_and_decision_ring(monkeypatch):
    fed = _fed(2, reprobe_cooldown_s=60.0)
    monkeypatch.setattr(fed.channels[0].client, "_server_stats_maybe",
                        lambda: {"stub": 0})
    monkeypatch.setattr(fed.channels[1].client, "_server_stats_maybe",
                        lambda: {"stub": 1})
    monkeypatch.setattr(fed, "_channel_verify",
                        lambda c, jb, h: np.zeros(len(jb), bool))
    for _ in range(4):
        fed._verify_ed25519_device(_jobs(4))
    fs = fed.federation_stats()
    assert fs["n_hosts"] == 2 and fs["healthy_hosts"] == 2
    assert fs["dispatches"] == 4
    # Serial batches always see zero depth: all land on host 0.
    assert fs["routing_share_by_host"][fed.channels[0].address] == 1.0
    assert fs["routing_share_by_host"][fed.channels[1].address] == 0.0
    assert len(fs["recent_decisions"]) == 4
    d = fs["recent_decisions"][-1]
    assert d["host"] == fed.channels[0].address and d["hedged"] is False
    assert set(d["depths"]) == {c.address for c in fed.channels}
    # The node_metrics seam: same duck type the single sidecar stamps.
    sc = fed.sidecar_stats()
    assert sc["address"] == ",".join(c.address for c in fed.channels)
    assert sc["federation"]["dispatches"] == 4
    assert sc["batches"] == 0  # client-side wire counters never ran


def test_qos_hint_hands_off_to_winning_channel(monkeypatch):
    # The real _channel_verify runs here (only the channel CLIENT's wire
    # method is stubbed): the advisory hint must reach the chosen host's
    # client so the remote deadline scheduler can order around it.
    fed = _fed(2)
    seen = {}

    def client_verify(jb):
        seen["hint"] = fed.channels[0].client.qos_hint
        return np.zeros(len(jb), bool)

    monkeypatch.setattr(fed.channels[0].client, "_verify_ed25519_device",
                        client_verify)
    fed.qos_hint = (LANE_CODE_BULK, 123456789)
    fed._verify_ed25519_device(_jobs(2))
    assert seen["hint"] == (LANE_CODE_BULK, 123456789)


# -- satellite: the per-endpoint server-stats cache --------------------------


def test_server_stats_cache_is_per_endpoint(monkeypatch):
    from corda_tpu.node import verify_client
    from corda_tpu.node.verify_client import SidecarVerifier

    client = SidecarVerifier("ep-a")
    fetched = []

    def fake_fetch(address, timeout=2.0):
        fetched.append(address)
        return {"endpoint": address}

    monkeypatch.setattr(verify_client, "fetch_sidecar_stats", fake_fetch)
    assert client._server_stats_maybe() == {"endpoint": "ep-a"}
    # Within the 5s window the cached snapshot serves — no second fetch.
    assert client._server_stats_maybe() == {"endpoint": "ep-a"}
    assert fetched == ["ep-a"]
    # The latent single-slot bug: after an address change, the old cache
    # entry must NEVER masquerade as the new endpoint's snapshot.
    client.address = "ep-b"
    assert client._server_stats_maybe() == {"endpoint": "ep-b"}
    assert fetched == ["ep-a", "ep-b"]
    # ... and flipping back within the window hits ep-a's own entry.
    client.address = "ep-a"
    assert client._server_stats_maybe() == {"endpoint": "ep-a"}
    assert fetched == ["ep-a", "ep-b"]


# -- the node's verifier selection (federation-off bit-identity) -------------


def _cfg(tmp_path, **batch_kw):
    from corda_tpu.node.config import BatchConfig, NodeConfig

    return NodeConfig(name="n", base_dir=tmp_path,
                      batch=BatchConfig(**batch_kw))


def test_select_verifier_federation_off_is_bit_identical(tmp_path,
                                                         monkeypatch):
    from corda_tpu.node.node import _make_verifier, _select_batch_verifier
    from corda_tpu.node.verify_client import SidecarVerifier

    monkeypatch.delenv("CORDA_TPU_FEDERATION", raising=False)
    monkeypatch.delenv("CORDA_TPU_SIDECAR", raising=False)
    # No federation, no sidecar: exactly the local provider the
    # pre-federation tree selected.
    v = _select_batch_verifier(_cfg(tmp_path))
    assert type(v) is type(_make_verifier("cpu"))
    # Single sidecar: exactly the single-host client, NOT a one-host
    # federation — the single-sidecar wire path stays bit-identical.
    v = _select_batch_verifier(_cfg(tmp_path, sidecar="/tmp/sc.sock",
                                    sidecar_deadline_ms=1234.0))
    assert type(v) is SidecarVerifier
    assert v.address == "/tmp/sc.sock"
    assert v.deadline_s == pytest.approx(1.234)


def test_select_verifier_federation_config_and_env(tmp_path, monkeypatch):
    from corda_tpu.node.node import _select_batch_verifier

    monkeypatch.delenv("CORDA_TPU_FEDERATION", raising=False)
    v = _select_batch_verifier(_cfg(
        tmp_path, federation_hosts="hostA.sock, hostB.sock",
        sidecar="/ignored.sock", sidecar_deadline_ms=500.0))
    assert isinstance(v, FederatedVerifier)
    # federation_hosts takes precedence over sidecar; whitespace-tolerant.
    assert [c.address for c in v.channels] == ["hostA.sock", "hostB.sock"]
    assert v.deadline_s == pytest.approx(0.5)
    # The env var the driver plants works like the config key.
    monkeypatch.setenv("CORDA_TPU_FEDERATION", "h0.sock,h1.sock,h2.sock")
    v = _select_batch_verifier(_cfg(tmp_path))
    assert isinstance(v, FederatedVerifier)
    assert len(v.channels) == 3


def test_batch_config_parses_federation_hosts_list_and_string(tmp_path):
    from corda_tpu.node.config import NodeConfig

    raw = {"name": "n", "base_dir": str(tmp_path),
           "batch": {"federation_hosts": ["a.sock", "b.sock"]}}
    cfg = NodeConfig.from_dict(raw)
    assert cfg.batch.federation_hosts == "a.sock,b.sock"
    raw["batch"] = {"federation_hosts": "a.sock,b.sock"}
    assert NodeConfig.from_dict(raw).batch.federation_hosts == \
        "a.sock,b.sock"
    assert NodeConfig.from_dict(
        {"name": "n", "base_dir": str(tmp_path)}).batch.federation_hosts \
        == ""
