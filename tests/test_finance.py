"""Cash contract rules + TwoPartyTradeFlow DvP end-to-end.

Mirrors the reference's CashTests (reference: finance/src/test/kotlin/net/
corda/contracts/asset/CashTests.kt) at the unit tier and
TwoPartyTradeProtocolTests at the MockNetwork tier. Makes BASELINE configs
2 and 4 (trades via validating notary; multi-sig cash) runnable.
"""

import pytest

from corda_tpu.contracts.dsl import RequirementFailed
from corda_tpu.contracts.structures import Command, Issued
from corda_tpu.contracts.verification import ContractRejection
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.finance import Amount, Cash, CashExit, CashIssue, CashMove, CashState
from corda_tpu.finance.cash import InsufficientBalanceException
from corda_tpu.flows.notary import NotaryClientFlow
from corda_tpu.testing.mock_network import MockNetwork
from corda_tpu.transactions.builder import TransactionBuilder


MEGA_KEY = KeyPair.generate(b"\x31" * 32)
MEGA_CORP = Party.of("MegaCorp", MEGA_KEY.public)
ALICE_KEY = KeyPair.generate(b"\x32" * 32)
ALICE = Party.of("Alice", ALICE_KEY.public)
BOB_KEY = KeyPair.generate(b"\x33" * 32)
BOB = Party.of("Bob", BOB_KEY.public)
NOTARY_KEY = KeyPair.generate(b"\x34" * 32)
NOTARY = Party.of("Notary", NOTARY_KEY.public)

USD = "USD"


def issued_usd(qty):
    return Amount(qty, Issued(MEGA_CORP.ref(b"\x01"), USD))


def issue_tx(qty=1000, owner=None, sign=True):
    tx = Cash.generate_issue(
        Amount(qty, USD), MEGA_CORP.ref(b"\x01"),
        owner or ALICE.owning_key, NOTARY, nonce=7)
    if sign:
        tx.sign_with(MEGA_KEY)
    return tx


class FakeStorage:
    def __init__(self, txs):
        self._txs = {t.id: t for t in txs}

    def get_transaction(self, id):
        return self._txs.get(id)


class FakeServices:
    """Just enough ServiceHub for to_ledger_transaction in unit tests."""

    def __init__(self, txs=(), parties=()):
        from types import SimpleNamespace

        self.storage_service = SimpleNamespace(
            validated_transactions=FakeStorage(txs),
            attachments=SimpleNamespace(open_attachment=lambda _id: None),
        )
        self._parties = {p.owning_key: p for p in parties}
        self.identity_service = SimpleNamespace(
            party_from_key=lambda k: self._parties.get(k))

    def load_state(self, ref):
        stx = self.storage_service.validated_transactions.get_transaction(
            ref.txhash)
        return None if stx is None else stx.tx.outputs[ref.index]


class TestCashRules:
    def test_issue_ok(self):
        stx = issue_tx().to_signed_transaction()
        ltx = stx.tx.to_ledger_transaction(FakeServices())
        ltx.verify()  # issuer signed, outputs > inputs

    def test_issue_without_issuer_signature_rejected(self):
        tx = TransactionBuilder(notary=NOTARY)
        tx.add_output_state(CashState(issued_usd(500), ALICE.owning_key))
        tx.add_command(Command(CashIssue(1), (ALICE.owning_key,)))  # not issuer
        wtx = tx.to_wire_transaction()
        with pytest.raises(ContractRejection, match="issuer"):
            wtx.to_ledger_transaction(FakeServices()).verify()

    def test_move_conserves_value(self):
        issue_stx = issue_tx().to_signed_transaction()
        prior = issue_stx.tx.out_ref(0)
        tx = TransactionBuilder(notary=NOTARY)
        tx.add_input_state(prior)
        tx.add_output_state(CashState(issued_usd(400), BOB.owning_key))
        tx.add_output_state(CashState(issued_usd(600), ALICE.owning_key))
        tx.add_command(Command(CashMove(), (ALICE.owning_key,)))
        wtx = tx.to_wire_transaction()
        wtx.to_ledger_transaction(FakeServices([issue_stx])).verify()

    def test_move_that_creates_money_rejected(self):
        issue_stx = issue_tx().to_signed_transaction()
        prior = issue_stx.tx.out_ref(0)
        tx = TransactionBuilder(notary=NOTARY)
        tx.add_input_state(prior)
        tx.add_output_state(CashState(issued_usd(1001), BOB.owning_key))
        tx.add_command(Command(CashMove(), (ALICE.owning_key,)))
        with pytest.raises(ContractRejection, match="amounts balance"):
            tx.to_wire_transaction().to_ledger_transaction(
                FakeServices([issue_stx])).verify()

    def test_move_without_owner_signature_rejected(self):
        issue_stx = issue_tx().to_signed_transaction()
        prior = issue_stx.tx.out_ref(0)
        tx = TransactionBuilder(notary=NOTARY)
        tx.add_input_state(prior)
        tx.add_output_state(CashState(issued_usd(1000), BOB.owning_key))
        tx.add_command(Command(CashMove(), (BOB.owning_key,)))  # wrong signer
        with pytest.raises(ContractRejection, match="owner has signed"):
            tx.to_wire_transaction().to_ledger_transaction(
                FakeServices([issue_stx])).verify()

    def test_exit_burns_exact_amount(self):
        issue_stx = issue_tx().to_signed_transaction()
        prior = issue_stx.tx.out_ref(0)
        tx = TransactionBuilder(notary=NOTARY)
        Cash.generate_exit(tx, issued_usd(250), [prior])
        wtx = tx.to_wire_transaction()
        wtx.to_ledger_transaction(FakeServices([issue_stx])).verify()
        remaining = [o.data for o in wtx.outputs]
        assert len(remaining) == 1 and remaining[0].amount.quantity == 750

    def test_different_issuers_do_not_mix(self):
        other_issuer = Issued(ALICE.ref(b"\x02"), USD)
        issue_stx = issue_tx().to_signed_transaction()
        prior = issue_stx.tx.out_ref(0)
        tx = TransactionBuilder(notary=NOTARY)
        tx.add_input_state(prior)
        # Output claims a different issuer: that group has no inputs and no
        # issue command -> rejected; the input group loses value -> rejected.
        tx.add_output_state(CashState(Amount(1000, other_issuer), BOB.owning_key))
        tx.add_command(Command(CashMove(), (ALICE.owning_key,)))
        with pytest.raises(ContractRejection):
            tx.to_wire_transaction().to_ledger_transaction(
                FakeServices([issue_stx])).verify()

    def test_generate_spend_coin_selection_and_change(self):
        issue_stx = issue_tx(qty=300).to_signed_transaction()
        issue_stx2 = issue_tx(qty=500).to_signed_transaction()
        tx = TransactionBuilder(notary=NOTARY)
        owners = Cash.generate_spend(
            tx, Amount(600, USD), BOB.owning_key,
            [issue_stx.tx.out_ref(0), issue_stx2.tx.out_ref(0)])
        assert owners == [ALICE.owning_key]
        paid = sum(o.data.amount.quantity for o in tx.outputs
                   if o.data.owner == BOB.owning_key)
        change = sum(o.data.amount.quantity for o in tx.outputs
                     if o.data.owner == ALICE.owning_key)
        assert paid == 600 and change == 200

    def test_generate_spend_insufficient(self):
        issue_stx = issue_tx(qty=100).to_signed_transaction()
        tx = TransactionBuilder(notary=NOTARY)
        with pytest.raises(InsufficientBalanceException):
            Cash.generate_spend(tx, Amount(600, USD), BOB.owning_key,
                                [issue_stx.tx.out_ref(0)])


class TestTwoPartyTrade:
    def _setup(self):
        net = MockNetwork()
        notary = net.create_notary_node("Notary", validating=True)
        seller = net.create_node("Seller")
        buyer = net.create_node("Buyer")
        return net, notary, seller, buyer

    def test_dvp_trade_settles_atomically(self):
        from corda_tpu.finance.trade import BuyerFlow, SellerFlow
        from corda_tpu.testing.dummies import DummyContract

        net, notary, seller, buyer = self._setup()
        try:
            # Buyer self-issues cash (as a cash issuer) and records it.
            cash_issue = Cash.generate_issue(
                Amount(1_000, USD), buyer.identity.ref(b"\x01"),
                buyer.identity.owning_key, notary.identity)
            cash_issue.sign_with(buyer.key)
            cash_stx = cash_issue.to_signed_transaction()
            buyer.record_transaction(cash_stx)

            # Seller owns a dummy asset.
            asset_issue = DummyContract.generate_initial(
                seller.identity.ref(b"\x02"), 42, notary.identity)
            asset_issue.sign_with(seller.key)
            asset_stx = asset_issue.to_signed_transaction()
            seller.record_transaction(asset_stx)
            asset = asset_stx.tx.out_ref(0)

            buyer.register_initiated_flow(
                "SellerFlow",
                lambda party: BuyerFlow(party, Amount(800, USD),
                                        notary.identity))
            handle = seller.start_flow(
                SellerFlow(buyer.identity, asset, Amount(750, USD)))
            net.run_network()
            final = handle.result.result()

            # Atomic settlement: the final tx moves BOTH legs.
            wtx = final.tx
            assert asset.ref in wtx.inputs
            asset_outs = [o.data for o in wtx.outputs
                          if not isinstance(o.data, CashState)]
            assert [o.owner for o in asset_outs] == [buyer.identity.owning_key]
            paid = sum(o.data.amount.quantity for o in wtx.outputs
                       if isinstance(o.data, CashState)
                       and o.data.owner == seller.identity.owning_key)
            assert paid == 750
            # Notary committed the inputs exactly once.
            assert notary.uniqueness_provider.committed_count == len(wtx.inputs)
            # Both sides recorded the final transaction (broadcast).
            for node in (seller, buyer):
                assert node.services.storage_service.validated_transactions \
                    .get_transaction(final.id) is not None
            # Buyer's vault: asset in, spent cash out, change in.
            buyer_states = buyer.services.vault_service.current_vault.states
            cash_left = sum(s.state.data.amount.quantity for s in buyer_states
                            if isinstance(s.state.data, CashState))
            assert cash_left == 250
        finally:
            net.stop_nodes()

    def test_trade_rejected_when_price_too_high(self):
        from corda_tpu.finance.trade import (
            BuyerFlow, SellerFlow, UnacceptablePriceException,
        )
        from corda_tpu.testing.dummies import DummyContract

        net, notary, seller, buyer = self._setup()
        try:
            asset_issue = DummyContract.generate_initial(
                seller.identity.ref(b"\x02"), 43, notary.identity)
            asset_issue.sign_with(seller.key)
            asset_stx = asset_issue.to_signed_transaction()
            seller.record_transaction(asset_stx)

            buyer.register_initiated_flow(
                "SellerFlow",
                lambda party: BuyerFlow(party, Amount(100, USD),
                                        notary.identity))
            handle = seller.start_flow(SellerFlow(
                buyer.identity, asset_stx.tx.out_ref(0), Amount(750, USD)))
            net.run_network()
            with pytest.raises(Exception):
                handle.result.result()
            assert notary.uniqueness_provider.committed_count == 0
        finally:
            net.stop_nodes()


class TestCommodity:
    """CommodityContract rides the shared OnLedgerAsset scaffolding
    (reference: CommodityContract.kt:36 — 'intentionally similar to Cash',
    same issue/move/exit command semantics over a non-cash token)."""

    def _parties(self):
        from corda_tpu.crypto.keys import KeyPair
        from corda_tpu.crypto.party import Party

        issuer = Party.of("Warehouse", KeyPair.generate(b"\x71" * 32).public)
        alice = Party.of("Alice", KeyPair.generate(b"\x72" * 32).public)
        bob = Party.of("Bob", KeyPair.generate(b"\x73" * 32).public)
        notary = Party.of("N", KeyPair.generate(b"\x74" * 32).public)
        return issuer, alice, bob, notary

    def test_issue_move_exit_lifecycle(self):
        from corda_tpu.contracts.structures import Issued, StateAndRef
        from corda_tpu.finance import (
            Amount,
            Commodity,
            CommodityState,
        )
        from corda_tpu.finance.commodity import COMMODITY_PROGRAM_ID
        from corda_tpu.testing.ledger_dsl import ledger

        issuer, alice, bob, notary = self._parties()
        gold = Commodity("XAU", "Gold", 3)
        token = Issued(issuer.ref(b"\x01"), gold)
        l = ledger(notary)

        # Issue 100oz to Alice: issuer signs.
        with l.transaction() as tx:
            tx.output(CommodityState(Amount(100, token), alice.owning_key))
            tx.command(COMMODITY_PROGRAM_ID.make_issue_command(1),
                       issuer.owning_key)
            tx.verifies()

        # Move 100oz Alice -> Bob: conserved, Alice signs.
        with l.transaction() as tx:
            tx.input(CommodityState(Amount(100, token), alice.owning_key))
            tx.output(CommodityState(Amount(100, token), bob.owning_key))
            tx.command(COMMODITY_PROGRAM_ID.make_move_command(),
                       alice.owning_key)
            tx.verifies()

        # A move that mints is rejected by conservation.
        with l.transaction() as tx:
            tx.input(CommodityState(Amount(100, token), alice.owning_key))
            tx.output(CommodityState(Amount(150, token), bob.owning_key))
            tx.command(COMMODITY_PROGRAM_ID.make_move_command(),
                       alice.owning_key)
            tx.fails_with("amounts balance")

        # Exit burns with issuer + owner signatures.
        with l.transaction() as tx:
            tx.input(CommodityState(Amount(100, token), bob.owning_key))
            tx.output(CommodityState(Amount(40, token), bob.owning_key))
            tx.command(
                COMMODITY_PROGRAM_ID.make_exit_command(Amount(60, token)),
                bob.owning_key, issuer.owning_key)
            tx.verifies()

    def test_generate_spend_selects_and_returns_change(self):
        from corda_tpu.contracts.structures import Issued, StateAndRef, StateRef
        from corda_tpu.contracts.structures import TransactionState
        from corda_tpu.crypto.hashes import SecureHash
        from corda_tpu.finance import Amount, Commodity, CommodityState
        from corda_tpu.finance.commodity import COMMODITY_PROGRAM_ID
        from corda_tpu.transactions.builder import TransactionBuilder

        issuer, alice, bob, notary = self._parties()
        oil = Commodity("OIL")
        token = Issued(issuer.ref(b"\x02"), oil)

        def sar(i, qty):
            return StateAndRef(
                TransactionState(
                    CommodityState(Amount(qty, token), alice.owning_key),
                    notary),
                StateRef(SecureHash.sha256(bytes([i])), 0))

        tx = TransactionBuilder(notary=notary)
        owners = COMMODITY_PROGRAM_ID.generate_spend(
            tx, Amount(130, oil), bob.owning_key, [sar(1, 100), sar(2, 100)])
        assert owners == [alice.owning_key]
        outs = [o.data for o in tx.outputs]
        quantities = sorted(
            (o.amount.quantity, o.owner == bob.owning_key) for o in outs)
        assert quantities == [(70, False), (130, True)]  # payment + change
