"""The full irs-demo composition: scheduler -> oracle -> fixing -> notary.

Mirrors the reference's IRS fixing cycle (reference: samples/irs-demo —
NodeSchedulerService launches FixingFlow on the fixing date; the flow
queries NodeInterestRates, embeds the Fix, gets the oracle's tear-off
signature and the counterparty's signature, and finalises through the
notary). Runs over real TCP nodes so the scheduler tick is the node's own
run loop.
"""

import time

import pytest

from corda_tpu.contracts.structures import Command, now_micros
from corda_tpu.finance.fixable_deal import (
    FixableDealState,
    FixingFlow,
    install_fixing_acceptor,
)
from corda_tpu.flows.oracle import Fix, FixOf, RateOracle
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node

import os
import sys
sys.path.insert(0, os.path.dirname(__file__))
from test_tcp_node import pump_until  # noqa: E402


LIBOR_3M = FixOf("LIBOR", 20_100, "3M")
RATE = 4_2500


def test_scheduled_fixing_end_to_end(tmp_path):
    notary = Node(NodeConfig(name="Notary", base_dir=tmp_path / "Notary",
                             notary="simple",
                             network_map=tmp_path / "m.json")).start()
    floater = Node(NodeConfig(name="Floater", base_dir=tmp_path / "Floater",
                              network_map=tmp_path / "m.json")).start()
    fixed = Node(NodeConfig(name="Fixed", base_dir=tmp_path / "Fixed",
                            network_map=tmp_path / "m.json")).start()
    oracle_node = Node(NodeConfig(name="Oracle",
                                  base_dir=tmp_path / "Oracle",
                                  network_map=tmp_path / "m.json")).start()
    nodes = [notary, floater, fixed, oracle_node]
    try:
        for n in nodes:
            n.refresh_netmap()
        RateOracle(oracle_node.smm, oracle_node.key, {LIBOR_3M: RATE})
        install_fixing_acceptor(fixed.smm)

        # Agree the deal through the REAL deal flow (both sign, notarised,
        # broadcast) — the creation tx passes contract verification during
        # the counterparty's resolution, and both vaults pick it up.
        from corda_tpu.flows.deal import DealAcceptorFlow, DealInstigatorFlow
        from corda_tpu.contracts.structures import TypeOnlyCommandData
        from corda_tpu.serialization.codec import register
        from dataclasses import dataclass

        @register
        @dataclass(frozen=True)
        class _Agree(TypeOnlyCommandData):
            pass

        fixed.smm.register_flow_initiator(
            "DealInstigatorFlow", lambda party: DealAcceptorFlow(party))
        deal = FixableDealState(
            party_a=floater.identity, party_b=fixed.identity,
            oracle=oracle_node.identity, fix_of=LIBOR_3M,
            fix_at_micros=now_micros() + 700_000, notional=1_000_000)
        h = floater.start_flow(DealInstigatorFlow(
            fixed.identity, deal, _Agree(), notary.identity))
        pump_until(nodes, lambda: h.result.done)
        h.result.result()

        # BOTH schedulers see the deal (each holds it); only the floater's
        # fixing flow acts — the counterparty's exits quietly.
        pump_until(nodes, lambda:
                   floater.scheduler.next_scheduled is not None
                   and fixed.scheduler.next_scheduled is not None)

        def fixed_everywhere():
            for node in (floater, fixed):
                states = node.services.vault_service.current_vault.states
                fixed_deals = [s for s in states
                               if isinstance(s.state.data, FixableDealState)
                               and s.state.data.fixed_value is not None]
                if len(fixed_deals) != 1:
                    return False
            return True

        pump_until(nodes, fixed_everywhere, timeout=25.0)
        # Verify the fixing everywhere: value came from the oracle, old deal
        # consumed, notary committed it.
        for node in (floater, fixed):
            states = node.services.vault_service.current_vault.states
            deals = [s.state.data for s in states
                     if isinstance(s.state.data, FixableDealState)]
            assert len(deals) == 1 and deals[0].fixed_value == RATE
        assert notary.uniqueness_provider.committed_count == 1
        # And nothing further is scheduled (the fixed deal has no next
        # activity).
        assert floater.scheduler.next_scheduled is None
    finally:
        for n in nodes:
            n.stop()


def test_unilateral_fixing_rejected_at_contract_level():
    """Regression: the ledger rule itself (not just the honest flows) must
    reject a fixing that lacks the counterparty's or the oracle's declared
    signature — otherwise one party could commit a fabricated rate."""
    from dataclasses import replace

    from corda_tpu.contracts.verification import ContractRejection
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.party import Party
    from corda_tpu.finance.fixable_deal import FixableDealState
    from corda_tpu.flows.oracle import Fix
    from corda_tpu.testing.ledger_dsl import ledger
    from corda_tpu.testing.dummies import DummyContract  # noqa: F401

    a = Party.of("A", KeyPair.generate(b"\x91" * 32).public)
    b = Party.of("B", KeyPair.generate(b"\x92" * 32).public)
    o = Party.of("O", KeyPair.generate(b"\x93" * 32).public)
    n = Party.of("N", KeyPair.generate(b"\x94" * 32).public)
    deal = FixableDealState(party_a=a, party_b=b, oracle=o,
                            fix_of=LIBOR_3M, fix_at_micros=1, notional=5)

    l = ledger(n)
    with l.transaction() as tx:  # only A signs: rejected
        tx.input(deal)
        tx.output(replace(deal, fixed_value=999_999))
        tx.command(Fix(LIBOR_3M, 999_999), a.owning_key)
        tx.fails_with("both parties sign")
    with l.transaction() as tx:  # A+B but no oracle: rejected
        tx.input(deal)
        tx.output(replace(deal, fixed_value=999_999))
        tx.command(Fix(LIBOR_3M, 999_999), a.owning_key, b.owning_key)
        tx.fails_with("oracle attests")
    with l.transaction() as tx:  # full signer set: accepted
        tx.input(deal)
        tx.output(replace(deal, fixed_value=RATE))
        tx.command(Fix(LIBOR_3M, RATE), a.owning_key, b.owning_key,
                   o.owning_key)
        tx.verifies()
