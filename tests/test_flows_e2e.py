"""End-to-end flow tests over MockNetwork — the minimum slice (SURVEY.md §7):
issue → move → notarise via batched verify → commit → broadcast, plus
double-spend rejection and checkpoint/restart recovery.

Mirrors the reference's NotaryServiceTests / StateMachineManagerTests /
TwoPartyTradeProtocolTests coverage (reference: node/src/test/kotlin/net/corda/
node/services/NotaryServiceTests.kt, .../statemachine/StateMachineManagerTests.kt).
"""

import pytest

from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.flows import (
    FinalityFlow,
    FlowLogic,
    NotaryClientFlow,
    NotaryConflict,
    NotaryException,
    register_flow,
)
from corda_tpu.testing import DummyContract
from corda_tpu.testing.mock_network import MockNetwork


@pytest.fixture()
def net():
    network = MockNetwork(verifier=CpuVerifier())
    yield network
    network.stop_nodes()


def make_parties(net):
    notary = net.create_notary_node("Notary")
    alice = net.create_node("Alice")
    bob = net.create_node("Bob")
    return notary, alice, bob


def issue_to(net, node, notary_party, magic=1):
    """Issue a dummy state on `node`'s ledger (no notary sig needed: no inputs)."""
    builder = DummyContract.generate_initial(
        node.identity.ref(b"\x00"), magic, notary_party
    )
    builder.sign_with(node.key)
    stx = builder.to_signed_transaction()
    node.record_transaction(stx)
    return stx


class TestNotarisation:
    def test_notarise_move(self, net):
        notary, alice, bob = make_parties(net)
        issue_stx = issue_to(net, alice, notary.identity, magic=7)
        prior = issue_stx.tx.out_ref(0)

        move = DummyContract.move(prior, bob.identity.owning_key)
        move.sign_with(alice.key)
        move_stx = move.to_signed_transaction(check_sufficient_signatures=False)

        handle = alice.start_flow(NotaryClientFlow(move_stx))
        net.run_network()

        sig = handle.result.result()
        assert sig.by in notary.identity.owning_key.keys
        sig.verify(move_stx.id.bytes)
        # The notary committed the input.
        assert notary.uniqueness_provider.committed_count == 1

    def test_double_spend_rejected(self, net):
        notary, alice, bob = make_parties(net)
        issue_stx = issue_to(net, alice, notary.identity, magic=8)
        prior = issue_stx.tx.out_ref(0)

        spend1 = DummyContract.move(prior, bob.identity.owning_key)
        spend1.sign_with(alice.key)
        stx1 = spend1.to_signed_transaction(check_sufficient_signatures=False)

        spend2 = DummyContract.move(prior, alice.identity.owning_key)
        spend2.sign_with(alice.key)
        stx2 = spend2.to_signed_transaction(check_sufficient_signatures=False)
        assert stx1.id != stx2.id

        h1 = alice.start_flow(NotaryClientFlow(stx1))
        net.run_network()
        h1.result.result()  # first spend accepted

        h2 = alice.start_flow(NotaryClientFlow(stx2))
        net.run_network()
        with pytest.raises(NotaryException) as exc:
            h2.result.result()
        assert isinstance(exc.value.error, NotaryConflict)

    def test_unsigned_transaction_rejected(self, net):
        notary, alice, bob = make_parties(net)
        issue_stx = issue_to(net, alice, notary.identity, magic=9)
        prior = issue_stx.tx.out_ref(0)

        move = DummyContract.move(prior, bob.identity.owning_key)
        move.sign_with(bob.key)  # wrong key: owner is alice
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        handle = alice.start_flow(NotaryClientFlow(stx))
        net.run_network()
        with pytest.raises(Exception):
            handle.result.result()


class TestFinality:
    def test_finality_notarises_and_broadcasts(self, net):
        notary, alice, bob = make_parties(net)
        issue_stx = issue_to(net, alice, notary.identity, magic=10)
        prior = issue_stx.tx.out_ref(0)

        move = DummyContract.move(prior, bob.identity.owning_key)
        move.sign_with(alice.key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        handle = alice.start_flow(FinalityFlow(stx, (bob.identity,)))
        net.run_network()
        final_stx = handle.result.result()

        # Notary signature attached; both nodes recorded the transaction.
        assert len(final_stx.sigs) == 2
        assert (
            alice.services.storage_service.validated_transactions.get_transaction(
                stx.id
            )
            is not None
        )
        bob_stored = bob.services.storage_service.validated_transactions.get_transaction(
            stx.id
        )
        assert bob_stored is not None
        # Bob resolved the dependency (the issue tx) too.
        assert (
            bob.services.storage_service.validated_transactions.get_transaction(
                issue_stx.id
            )
            is not None
        )
        # Bob's vault sees the new state; alice's vault consumed hers.
        assert len(bob.services.vault_service.current_vault.states) == 1
        assert len(alice.services.vault_service.current_vault.states) == 0

    def test_batched_verification_actually_batches(self, net):
        """Concurrent notarisations verify in shared kernel batches."""
        notary, alice, bob = make_parties(net)
        stxs = []
        for i in range(4):
            issue_stx = issue_to(net, alice, notary.identity, magic=20 + i)
            prior = issue_stx.tx.out_ref(0)
            move = DummyContract.move(prior, bob.identity.owning_key)
            move.sign_with(alice.key)
            stxs.append(move.to_signed_transaction(check_sufficient_signatures=False))

        handles = [alice.start_flow(NotaryClientFlow(stx)) for stx in stxs]
        net.run_network()
        for h in handles:
            h.result.result()
        # Deferred flushing batches the 4 concurrent flows' checks into ONE
        # kernel call per phase: the clients' own-signature round and their
        # notary-response-signature round (2 on alice), and the notary's
        # request-validation round.
        assert alice.smm.metrics["verify_sigs"] >= 8  # 4 tx checks + 4 result sigs
        assert alice.smm.metrics["verify_batches"] == 2
        assert notary.smm.metrics["verify_sigs"] >= 4
        assert notary.smm.metrics["verify_batches"] <= 2


class TestSingleSigPump:
    def test_bad_signature_rejected_via_pump(self, net):
        """verify_signature_batched delivers SignatureError for a corrupted
        signature (the notary-response validation path)."""
        from corda_tpu.crypto.keys import SignatureError
        from corda_tpu.flows.api import FlowLogic, register_flow

        _, alice, _ = make_parties(net)
        content = b"notary-signed-content-0123456789ab"
        good = alice.key.sign(content)
        bad = type(good)(good.bytes[:5] + bytes([good.bytes[5] ^ 1])
                         + good.bytes[6:], good.by)

        @register_flow
        class CheckSigFlow(FlowLogic):
            def __init__(self, sig):
                self.sig = sig

            def call(self):
                yield self.verify_signature_batched(self.sig, content)
                return "ok"

        h_good = alice.start_flow(CheckSigFlow(good))
        h_bad = alice.start_flow(CheckSigFlow(bad))
        net.run_network()
        assert h_good.result.result() == "ok"
        with pytest.raises(SignatureError):
            h_bad.result.result()


class TestRecovery:
    def test_notary_restart_mid_flow(self, net):
        """Kill the notary between request arrival and processing; restore
        from checkpoints must complete the protocol (reference capability:
        restoreFibersFromCheckpoints, StateMachineManager.kt:190-226)."""
        notary, alice, bob = make_parties(net)
        issue_stx = issue_to(net, alice, notary.identity, magic=30)
        prior = issue_stx.tx.out_ref(0)
        move = DummyContract.move(prior, bob.identity.owning_key)
        move.sign_with(alice.key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        handle = alice.start_flow(NotaryClientFlow(stx))
        # Deliver messages one at a time; crash the notary mid-protocol.
        pumped = 0
        while net.messaging_network.pump():
            pumped += 1
            if pumped == 2:
                notary = notary.restart()
        net.run_network()
        sig = handle.result.result()
        sig.verify(stx.id.bytes)

    def test_client_restart_resumes_from_checkpoint(self, net):
        notary, alice, bob = make_parties(net)
        issue_stx = issue_to(net, alice, notary.identity, magic=31)
        prior = issue_stx.tx.out_ref(0)
        move = DummyContract.move(prior, bob.identity.owning_key)
        move.sign_with(alice.key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        alice.start_flow(NotaryClientFlow(stx))
        # Crash the client before any response arrives.
        alice = alice.restart()
        net.run_network()
        # The restored flow finished: the input got committed exactly once.
        assert notary.uniqueness_provider.committed_count == 1


class TestKillAtEveryStep:
    """Property: the notarisation protocol completes regardless of where a
    node crashes, because every suspension is checkpointed (SURVEY.md §7 hard
    part #3; reference: TwoPartyTradeProtocolTests mid-flow restarts)."""

    @pytest.mark.parametrize("crash_after", [1, 2, 3, 4, 5])
    @pytest.mark.parametrize("victim", ["client", "notary"])
    def test_crash_at_step(self, crash_after, victim):
        net = MockNetwork(verifier=CpuVerifier())
        try:
            notary, alice, bob = make_parties(net)
            issue_stx = issue_to(net, alice, notary.identity, magic=50 + crash_after)
            prior = issue_stx.tx.out_ref(0)
            move = DummyContract.move(prior, bob.identity.owning_key)
            move.sign_with(alice.key)
            stx = move.to_signed_transaction(check_sufficient_signatures=False)

            alice.start_flow(NotaryClientFlow(stx))
            steps = 0
            crashed = False
            while True:
                progressed = net.messaging_network.pump()
                if not progressed:
                    flushed = sum(
                        n.smm.flush_pending_verifies() for n in net.nodes
                    )
                    if not flushed:
                        break
                steps += 1
                if steps == crash_after and not crashed:
                    crashed = True
                    if victim == "client":
                        alice = alice.restart()
                    else:
                        notary = notary.restart()
            net.run_network()
            assert notary.uniqueness_provider.committed_count == 1, (
                f"crash_after={crash_after} victim={victim}: protocol did not complete"
            )
        finally:
            net.stop_nodes()


class TestSessionErrors:
    def test_unregistered_flow_rejected(self, net):
        notary, alice, bob = make_parties(net)

        @register_flow
        class UnknownInitiator(FlowLogic):
            def __init__(self, other):
                self.other = other

            def call(self):
                reply = yield self.send_and_receive(self.other, "hello?")
                return reply

        handle = alice.start_flow(UnknownInitiator(bob.identity))
        net.run_network()
        with pytest.raises(Exception):
            handle.result.result()


class TestNotaryChange:
    def test_notary_change_unanimous_consent(self):
        """A shared state moves to a new notary once every participant signs
        (reference: NotaryChangeTests.kt over AbstractStateReplacementFlow)."""
        from corda_tpu.flows.state_replacement import (
            NotaryChangeFlow,
            install_notary_change_acceptor,
        )
        from corda_tpu.testing.dummies import DummyMultiOwnerState
        from corda_tpu.contracts.structures import Command
        from corda_tpu.testing.dummies import DummyCreate
        from corda_tpu.transactions.builder import TransactionBuilder

        net = MockNetwork(verifier=CpuVerifier())
        try:
            notary_a = net.create_notary_node("NotaryA")
            notary_b = net.create_notary_node("NotaryB")
            alice = net.create_node("Alice")
            bob = net.create_node("Bob")
            install_notary_change_acceptor(bob.smm)

            # A state co-owned by alice and bob, on notary A.
            state = DummyMultiOwnerState(
                7, (alice.identity.owning_key, bob.identity.owning_key))
            tx = TransactionBuilder(notary=notary_a.identity)
            tx.add_output_state(state)
            tx.add_command(Command(DummyCreate(), (alice.identity.owning_key,)))
            tx.sign_with(alice.key)
            issue_stx = tx.to_signed_transaction()
            alice.record_transaction(issue_stx)
            bob.record_transaction(issue_stx)

            handle = alice.start_flow(NotaryChangeFlow(
                issue_stx.tx.out_ref(0), notary_b.identity))
            net.run_network()
            new_ref = handle.result.result()
            assert new_ref.state.notary == notary_b.identity
            assert new_ref.state.data == state
            # The old notary committed the consumed input exactly once.
            assert notary_a.uniqueness_provider.committed_count == 1
            # Both parties recorded the replacement.
            for node in (alice, bob):
                assert node.services.storage_service.validated_transactions \
                    .get_transaction(new_ref.ref.txhash) is not None
        finally:
            net.stop_nodes()

    def test_notary_change_same_notary_rejected(self):
        from corda_tpu.flows.state_replacement import (
            NotaryChangeFlow,
            StateReplacementException,
        )

        net = MockNetwork(verifier=CpuVerifier())
        try:
            notary, alice, bob = make_parties(net)
            issue_stx = issue_to(net, alice, notary.identity, magic=77)
            handle = alice.start_flow(NotaryChangeFlow(
                issue_stx.tx.out_ref(0), notary.identity))
            net.run_network()
            with pytest.raises(StateReplacementException):
                handle.result.result()
        finally:
            net.stop_nodes()
