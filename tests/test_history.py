"""History auditor fixtures: each failure mode must be CAUGHT.

The checker (testing/history.py) is pure data-in/verdict-out, so these
fixtures build client histories and ledger unions by hand and prove the
partition soak's gate bit actually trips on a lost ack, a split-brain
double-spend, a lying rejection, a minority commit, and a hole in the
history itself — a checker that passes everything would make the whole
partition plane theater.
"""

from corda_tpu.testing.history import History, HistoryEvent, check_history

import pytest


def _history(*ops):
    """ops: (request_id, tx_id, refs, outcome) tuples."""
    h = History()
    for rid, tx, refs, outcome in ops:
        h.record_invoke("c1", rid, tx, refs=refs)
        if outcome is not None:
            h.record_outcome("c1", rid, outcome)
    return h


def test_clean_run_is_linearizable():
    h = _history(("r1", "tx1", ("ref1",), "ok"),
                 ("r2", "tx2", ("ref2",), "fail"),
                 ("r3", "tx3", ("ref3",), "timeout"))
    # tx2 rejected (absent), tx3 timed out and resolved committed.
    v = check_history(h, {"tx1", "tx3"},
                      consumed=[("ref1", "tx1"), ("ref3", "tx3"),
                                # replication duplicates are expected
                                ("ref1", "tx1")])
    assert v["history_linearizable"] is True
    assert v["invoked"] == 3
    assert v["acked_ok"] == 1
    assert v["acked_fail"] == 1
    assert v["timeouts"] == 1
    assert v["timeouts_resolved_committed"] == 1
    assert v["timeouts_resolved_aborted"] == 0
    assert not v["lost_acks"] and not v["double_spends"]


def test_lost_ack_caught():
    # Client was told tx1 committed; the ledger never heard of it — a
    # leader acked before quorum and the cut ate the commit.
    h = _history(("r1", "tx1", ("ref1",), "ok"))
    v = check_history(h, set())
    assert v["history_linearizable"] is False
    assert v["lost_acks"] == ["r1"]


def test_double_spend_caught():
    # Two members on opposite sides of a split each committed a
    # different spender of ref1 — the smoking gun lives in the union.
    h = _history(("r1", "tx1", ("ref1",), "ok"),
                 ("r2", "tx2", ("ref1",), "ok"))
    v = check_history(h, {"tx1", "tx2"},
                      consumed=[("ref1", "tx1"), ("ref1", "tx2")])
    assert v["history_linearizable"] is False
    assert v["double_spends"] == [{"ref": "ref1",
                                   "txs": ["tx1", "tx2"]}]


def test_fail_conflict_caught():
    # Client got a FINAL rejection yet the tx sits committed — the
    # reject and the commit cannot both be true.
    h = _history(("r1", "tx1", ("ref1",), "fail"))
    v = check_history(h, {"tx1"}, consumed=[("ref1", "tx1")])
    assert v["history_linearizable"] is False
    assert v["fail_conflicts"] == ["r1"]


def test_minority_commit_fails_the_gate():
    # A perfectly clean history still fails if the minority side's
    # committed rows advanced while the cut held.
    h = _history(("r1", "tx1", ("ref1",), "ok"))
    v = check_history(h, {"tx1"}, consumed=[("ref1", "tx1")],
                      minority_commits=2)
    assert v["history_linearizable"] is False
    assert v["minority_commits"] == 2


def test_unresolved_invoke_fails_loudly():
    # The harness records a timeout for every op it abandons; a hole
    # means the history itself is broken — under-checking is failure.
    h = _history(("r1", "tx1", ("ref1",), None))
    v = check_history(h, {"tx1"})
    assert v["history_linearizable"] is False
    assert v["unresolved"] == ["r1"]


def test_duplicate_outcomes_flagged():
    h = History()
    h.record_invoke("c1", "r1", "tx1", refs=("ref1",))
    h.record_outcome("c1", "r1", "ok")
    h.record_outcome("c1", "r1", "fail")
    v = check_history(h, {"tx1"}, consumed=[("ref1", "tx1")])
    assert v["history_linearizable"] is False
    assert v["duplicate_outcomes"] == ["r1"]


def test_timeout_may_resolve_either_way():
    h = _history(("r1", "tx1", ("ref1",), "timeout"),
                 ("r2", "tx2", ("ref2",), "timeout"))
    v = check_history(h, {"tx1"}, consumed=[("ref1", "tx1")])
    assert v["history_linearizable"] is True
    assert v["timeouts_resolved_committed"] == 1
    assert v["timeouts_resolved_aborted"] == 1


def test_unknown_outcome_kind_rejected():
    h = History()
    with pytest.raises(ValueError):
        h.record_outcome("c1", "r1", "maybe")


def test_plain_event_iterable_accepted():
    events = [HistoryEvent("invoke", "c1", "r1", "tx1", ("ref1",)),
              HistoryEvent("ok", "c1", "r1")]
    v = check_history(events, {"tx1"})
    assert v["history_linearizable"] is True
    assert v["events"] == 2


def test_history_cap_bounds_memory():
    h = History(cap=10)
    for i in range(25):
        h.record_invoke("c1", f"r{i}", f"tx{i}")
    assert len(h) == 10
