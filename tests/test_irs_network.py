"""The universal IRS driven over the network: oracle fixing with tear-off
signature, netted settlement, notarisation, broadcast — per period.

Mirrors the reference's irs-demo flow composition (reference:
samples/irs-demo/.../flows/ — RatesFixFlow + FixingFlow through
NodeInterestRates.Oracle and the notary) with the product expressed on the
universal-contract DSL (experimental/.../universal/IRS.kt) instead of a
bespoke contract.
"""

import datetime as dt

import pytest

from corda_tpu.contracts.structures import StateRef
from corda_tpu.contracts.universal import (
    SCALE,
    RollOut,
    Transfer,
    eval_amount,
    generate_issue,
)
from corda_tpu.finance.irs import IrsFixFlow, IrsSettleFlow, interest_rate_swap
from corda_tpu.finance.types import Tenor, date_to_days
from corda_tpu.flows.api import FlowException
from corda_tpu.flows.finality import FinalityFlow
from corda_tpu.flows.notary import NotaryException
from corda_tpu.flows.oracle import FixOf, RateOracle
from corda_tpu.testing.mock_network import MockNetwork

START = date_to_days(dt.date(2016, 9, 1))
END = date_to_days(dt.date(2018, 9, 1))
LIBOR_AT_START = FixOf("LIBOR", START, "3M")
RATE = SCALE  # 1.0%


@pytest.fixture()
def net():
    network = MockNetwork()
    yield network


def build_network(network):
    notary = network.create_notary_node("Notary", validating=False)
    acme = network.create_node("ACME")
    highst = network.create_node("HighSt")
    oracle_node = network.create_node("Oracle")
    RateOracle(oracle_node.smm, oracle_node.key, {LIBOR_AT_START: RATE})
    swap = interest_rate_swap(
        notional=50_000_000 * SCALE, currency="EUR",
        fixed_rate=SCALE // 2, floating_index="LIBOR", index_tenor="3M",
        oracle=oracle_node.identity, fixed_leg_payer=acme.identity,
        floating_leg_payer=highst.identity, start_day=START, end_day=END,
        frequency=Tenor("3M"))
    builder = generate_issue(swap, highst.identity.ref(b"\x01"),
                             notary.identity)
    builder.sign_with(highst.key)
    builder.sign_with(acme.key)  # both legs are liable -> both sign issue
    issue_stx = builder.to_signed_transaction()
    h = highst.start_flow(FinalityFlow(
        issue_stx, (highst.identity, acme.identity)))
    network.run_network()
    h.result.result()
    return notary, acme, highst, oracle_node, issue_stx


def test_full_period_over_network(net):
    notary, acme, highst, oracle_node, issue_stx = build_network(net)
    # both vaults hold the swap
    for node in (acme, highst):
        assert any(
            isinstance(s.state.data.details, RollOut)
            for s in node.services.vault_service.current_vault.states)

    # -- fix the period via the oracle (tear-off signature)
    h = highst.start_flow(IrsFixFlow(
        StateRef(issue_stx.id, 0), oracle_node.identity, acme.identity))
    net.run_network()
    fixed_stx = h.result.result()
    oracle_keys = oracle_node.identity.owning_key.keys
    assert any(sig.by in oracle_keys for sig in fixed_stx.sigs), \
        "oracle's tear-off signature must ride the fixing transaction"

    # -- settle the period: floating 1.0% > fixed 0.5%, HighSt pays ACME
    h2 = acme.start_flow(IrsSettleFlow(
        StateRef(fixed_stx.id, 0), highst.identity))
    net.run_network()
    settle_stx = h2.result.result()
    outs = [o.data.details for o in settle_stx.tx.outputs]
    transfers = [d for d in outs if isinstance(d, Transfer)]
    rolls = [d for d in outs if isinstance(d, RollOut)]
    assert len(transfers) == 2 and len(rolls) == 1
    to_acme = next(t for t in transfers if t.to_party == acme.identity)
    to_highst = next(t for t in transfers if t.to_party == highst.identity)
    days = rolls[0].start_day - START
    assert eval_amount(None, to_acme.amount) == \
        (50_000_000 * SCALE * (SCALE // 2) * days) // (100 * SCALE * 365)
    assert eval_amount(None, to_highst.amount) == 0
    assert rolls[0].end_day == END

    # two commits: the fix consumed the issue output, the settle the fix's
    assert notary.uniqueness_provider.committed_count == 2

    # -- re-running the identical settle is idempotent (same Merkle id ->
    # the notary re-issues its signature rather than conflicting)
    h3 = acme.start_flow(IrsSettleFlow(
        StateRef(fixed_stx.id, 0), highst.identity))
    net.run_network()
    assert h3.result.result().id == settle_stx.id

    # -- but a DIFFERENT transaction consuming the settled input is a
    # double-spend: notary conflict
    from corda_tpu.contracts.structures import StateAndRef
    from corda_tpu.contracts.universal import UAction, UniversalState
    from corda_tpu.flows.notary import NotaryClientFlow
    from corda_tpu.transactions.builder import TransactionBuilder

    state = acme.services.load_state(StateRef(fixed_stx.id, 0))
    rogue = TransactionBuilder(notary=notary.identity)
    rogue.add_input_state(StateAndRef(state, StateRef(fixed_stx.id, 0)))
    rogue.add_output_state(UniversalState(
        state.data.parts, rolls[0]))  # drops the payment legs
    rogue.add_command(UAction("settle"), acme.identity.owning_key)
    rogue.sign_with(acme.key)
    h4 = acme.start_flow(NotaryClientFlow(
        rogue.to_signed_transaction(check_sufficient_signatures=False)))
    net.run_network()
    with pytest.raises(NotaryException):
        h4.result.result()


def test_fix_against_wrong_oracle_refused(net):
    notary, acme, highst, oracle_node, issue_stx = build_network(net)
    # ACME is not the pinned oracle: the flow refuses before any tx exists
    h = highst.start_flow(IrsFixFlow(
        StateRef(issue_stx.id, 0), acme.identity, acme.identity))
    net.run_network()
    with pytest.raises(FlowException, match="different oracle"):
        h.result.result()


def test_settle_before_period_end_fails_cleanly(net):
    """A period that has not ended yet must refuse to settle with a clean
    FlowException, not notarise a bogus window."""
    from corda_tpu.contracts.structures import now_micros
    from corda_tpu.contracts.universal import generate_issue as gen

    notary = net.create_notary_node("Notary", validating=False)
    acme = net.create_node("ACME2")
    highst = net.create_node("HighSt2")
    oracle_node = net.create_node("Oracle2")
    today = now_micros() // (86_400 * 1_000_000)
    fix_of = FixOf("LIBOR", today, "3M")
    RateOracle(oracle_node.smm, oracle_node.key, {fix_of: RATE})
    swap = interest_rate_swap(
        notional=1_000 * SCALE, currency="EUR", fixed_rate=SCALE // 2,
        floating_index="LIBOR", index_tenor="3M",
        oracle=oracle_node.identity, fixed_leg_payer=acme.identity,
        floating_leg_payer=highst.identity, start_day=today,
        end_day=today + 720, frequency=Tenor("3M"))
    builder = gen(swap, highst.identity.ref(b"\x02"), notary.identity)
    builder.sign_with(highst.key)
    builder.sign_with(acme.key)
    issue_stx = builder.to_signed_transaction()
    h = highst.start_flow(FinalityFlow(
        issue_stx, (highst.identity, acme.identity)))
    net.run_network()
    h.result.result()

    h1 = highst.start_flow(IrsFixFlow(
        StateRef(issue_stx.id, 0), oracle_node.identity, acme.identity))
    net.run_network()
    fixed_stx = h1.result.result()

    h2 = acme.start_flow(IrsSettleFlow(
        StateRef(fixed_stx.id, 0), highst.identity))
    net.run_network()
    with pytest.raises(FlowException, match="not ended yet"):
        h2.result.result()


def test_settle_requires_prior_fixing(net):
    notary, acme, highst, oracle_node, issue_stx = build_network(net)
    h = acme.start_flow(IrsSettleFlow(
        StateRef(issue_stx.id, 0), highst.identity))
    net.run_network()
    with pytest.raises(FlowException, match="fixing before settling"):
        h.result.result()


class TestIrsFixKillAtEveryStep:
    """The fixing protocol (oracle query -> tear-off sign -> notarise ->
    broadcast) completes exactly once no matter where the fixer or the
    oracle node crashes (the SURVEY §7 hard-part-#3 property applied to the
    deepest flow composition in the framework)."""

    @pytest.mark.parametrize("crash_after", [1, 2, 3, 4, 5, 6, 7, 8])
    @pytest.mark.parametrize("victim", ["fixer", "oracle"])
    def test_crash_at_step(self, crash_after, victim):
        from corda_tpu.contracts.universal import Actions
        from corda_tpu.crypto.provider import CpuVerifier

        net = MockNetwork(verifier=CpuVerifier())
        try:
            notary, acme, highst, oracle_node, issue_stx = build_network(net)
            highst.start_flow(IrsFixFlow(
                StateRef(issue_stx.id, 0), oracle_node.identity,
                acme.identity))
            steps, crashed = 0, False
            while True:
                progressed = net.messaging_network.pump()
                if not progressed:
                    flushed = sum(
                        n.smm.flush_pending_verifies() for n in net.nodes)
                    if not flushed:
                        break
                steps += 1
                if steps == crash_after and not crashed:
                    crashed = True
                    if victim == "fixer":
                        highst = highst.restart()
                    else:
                        oracle_node = oracle_node.restart()
                        # A rebooted oracle node re-wires its service at
                        # startup, exactly as a real node's plugin would.
                        RateOracle(oracle_node.smm, oracle_node.key,
                                   {LIBOR_AT_START: RATE})
            net.run_network()
            assert notary.uniqueness_provider.committed_count == 1, (
                f"crash_after={crash_after} victim={victim}: "
                "fixing did not commit exactly once")
            for node in (highst, acme):
                fixed = [s for s in
                         node.services.vault_service.current_vault.states
                         if isinstance(s.state.data.details, Actions)]
                assert len(fixed) == 1, (
                    f"crash_after={crash_after} victim={victim}: "
                    f"{node.name} vault lacks the fixed state")
        finally:
            net.stop_nodes()
