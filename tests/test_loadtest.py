"""Loadtest harness + max-wait micro-batch scheduler behaviour.

The scheduler contract (SURVEY.md §7 stage 6, VERDICT r1 item 8): pending
signature checks flush when the batch hits max_sigs OR the oldest waiter has
aged max_wait_ms — so throughput gets wide batches under load while p99
notarisation latency stays bounded when traffic is sparse.
"""

import pytest

import time

from corda_tpu.node.config import BatchConfig
from corda_tpu.tools.loadtest import run_loadtest


def test_firehose_batches_and_completes(tmp_path):
    result = run_loadtest(
        n_tx=30, notary="validating", verifier="cpu",
        batch=BatchConfig(max_sigs=4096, max_wait_ms=2.0),
        base_dir=str(tmp_path))
    assert result.tx_committed == 30
    assert result.tx_rejected == 0
    # Micro-batching collapsed the firehose: far fewer kernel calls than
    # signature checks (client-side 30 checks + notary-side 30 validations).
    assert result.sigs_verified >= 60
    assert result.verify_batches <= 12, (
        f"batching ineffective: {result.verify_batches} batches for "
        f"{result.sigs_verified} sigs")


def test_sparse_traffic_p99_bounded_by_max_wait(tmp_path):
    """A lone request must not wait for a full batch: the max-wait flush
    releases it within ~max_wait_ms plus scheduling slack."""
    result = run_loadtest(
        n_tx=1, notary="simple", verifier="cpu",
        batch=BatchConfig(max_sigs=100_000, max_wait_ms=2.0),
        base_dir=str(tmp_path))
    assert result.tx_committed == 1
    # One tx through sockets end-to-end; generous bound, but it proves the
    # flush did not wait for 100k signatures that never arrive.
    assert result.p99_ms < 2_000


def test_disruption_kill_and_rebuild_converges(tmp_path):
    result = run_loadtest(
        n_tx=30, notary="simple", disrupt="kill-notary", verifier="cpu",
        base_dir=str(tmp_path), max_seconds=60.0)
    assert result.disruptions, "disruption never fired"
    # Every transaction eventually settled exactly once despite the kill.
    assert result.tx_committed + result.tx_rejected == 30
    assert result.tx_committed >= 29  # rejects only if a retry raced itself


# ---------------------------------------------------------------------------
# Multi-process harness (driver-spawned OS-process nodes + loadgen cordapp)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_multiprocess_firehose_happy_path(tmp_path):
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    r = run_loadtest_multiprocess(
        n_tx=16, width=2, clients=2, notary="simple",
        base_dir=str(tmp_path), max_seconds=120.0)
    assert r.tx_committed == 16
    assert r.tx_rejected == 0
    assert r.clients == 2 and r.width == 2
    # Client pumps verified width sigs per move + the notary's response
    # signature (counted via RPC metric deltas across processes).
    assert r.sigs_verified >= 16 * 3
    assert r.sigs_per_sec > 0
    assert r.p50_ms <= r.p99_ms


@pytest.mark.slow
def test_multiprocess_open_loop_pacing(tmp_path):
    # rate_tx_s pacing stretches the measured phase to ~n/rate even though
    # the cluster could finish faster closed-loop.
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    r = run_loadtest_multiprocess(
        n_tx=30, width=1, clients=1, notary="simple", rate_tx_s=20.0,
        base_dir=str(tmp_path), max_seconds=120.0)
    assert r.tx_committed == 30
    assert r.duration_s >= 0.7 * (30 / 20.0)


@pytest.mark.slow
def test_multiprocess_kill_follower_converges(tmp_path):
    # Disruption.kt:18-60 'kill' against a real 3-process Raft cluster:
    # a follower is SIGKILLed mid-firehose and restarted from disk; every
    # transaction still commits exactly once.
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    r = run_loadtest_multiprocess(
        n_tx=200, width=2, clients=2, notary="raft",
        disrupt="kill-follower", disrupt_after_s=0.5,
        base_dir=str(tmp_path), max_seconds=300.0)
    assert r.disruptions, "kill disruption never fired"
    assert any("SIGKILL" in d for d in r.disruptions)
    assert r.tx_committed == 200
    assert r.tx_rejected == 0


@pytest.mark.slow
def test_multiprocess_sigstop_follower_converges(tmp_path):
    # The 'hang' primitive: a follower is frozen (SIGSTOP) for 2s — sockets
    # stay open, peers see an unresponsive node — then resumed. Quorum
    # holds and the firehose completes.
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    r = run_loadtest_multiprocess(
        n_tx=120, width=2, clients=2, notary="raft",
        disrupt="sigstop-follower", disrupt_after_s=0.3,
        base_dir=str(tmp_path), max_seconds=300.0)
    assert r.disruptions, "sigstop disruption never fired"
    assert any("SIGSTOP" in d for d in r.disruptions)
    assert r.tx_committed == 120


def test_open_loop_latency_sweep(tmp_path):
    # The sweep reports per-tx latency from scheduled submission: committed
    # counts are full and the distribution is a real one (p50 <= p99, not
    # the degenerate batch-completion measurement).
    from corda_tpu.tools.loadtest import run_latency_sweep

    res = run_latency_sweep(rates=(40.0,), n_tx=40,
                            base_dir=str(tmp_path))
    r = res[40.0]
    assert r.committed == 40
    assert r.p50_ms <= r.p90_ms <= r.p99_ms
    assert r.duration_s >= 0.6 * (40 / 40.0)
    # Self-describing stamps (homogeneous: every value is a member stamp
    # dict — the warm-wait scalar lives on the result object, not in here).
    assert res.node_stamps and all(
        isinstance(s, dict) for s in res.node_stamps.values())
    stamp = next(iter(res.node_stamps.values()))
    assert stamp["verifier"] is not None
    assert stamp["pipeline_depth"] == 2  # async pipeline on by default


@pytest.mark.slow
def test_latency_sweep_raft_validating_cluster(tmp_path):
    """Open-loop sweep against the FLAGSHIP config (3-member raft
    VALIDATING cluster through real OS processes — round-4 VERDICT item 4:
    BASELINE metric 2's p99 was only ever closed-loop for raft)."""
    from corda_tpu.tools.loadtest import run_latency_sweep

    sweep = run_latency_sweep(rates=(15.0,), n_tx=12, width=2,
                              notary="raft-validating",
                              base_dir=str(tmp_path), max_seconds=240.0)
    r = sweep[15.0]
    assert r.committed == 12
    assert r.rejected == 0
    assert r.p99_ms >= r.p50_ms > 0
