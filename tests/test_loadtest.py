"""Loadtest harness + max-wait micro-batch scheduler behaviour.

The scheduler contract (SURVEY.md §7 stage 6, VERDICT r1 item 8): pending
signature checks flush when the batch hits max_sigs OR the oldest waiter has
aged max_wait_ms — so throughput gets wide batches under load while p99
notarisation latency stays bounded when traffic is sparse.
"""

import time

from corda_tpu.node.config import BatchConfig
from corda_tpu.tools.loadtest import run_loadtest


def test_firehose_batches_and_completes(tmp_path):
    result = run_loadtest(
        n_tx=30, notary="validating", verifier="cpu",
        batch=BatchConfig(max_sigs=4096, max_wait_ms=2.0),
        base_dir=str(tmp_path))
    assert result.tx_committed == 30
    assert result.tx_rejected == 0
    # Micro-batching collapsed the firehose: far fewer kernel calls than
    # signature checks (client-side 30 checks + notary-side 30 validations).
    assert result.sigs_verified >= 60
    assert result.verify_batches <= 12, (
        f"batching ineffective: {result.verify_batches} batches for "
        f"{result.sigs_verified} sigs")


def test_sparse_traffic_p99_bounded_by_max_wait(tmp_path):
    """A lone request must not wait for a full batch: the max-wait flush
    releases it within ~max_wait_ms plus scheduling slack."""
    result = run_loadtest(
        n_tx=1, notary="simple", verifier="cpu",
        batch=BatchConfig(max_sigs=100_000, max_wait_ms=2.0),
        base_dir=str(tmp_path))
    assert result.tx_committed == 1
    # One tx through sockets end-to-end; generous bound, but it proves the
    # flush did not wait for 100k signatures that never arrive.
    assert result.p99_ms < 2_000


def test_disruption_kill_and_rebuild_converges(tmp_path):
    result = run_loadtest(
        n_tx=30, notary="simple", disrupt="kill-notary", verifier="cpu",
        base_dir=str(tmp_path), max_seconds=60.0)
    assert result.disruptions, "disruption never fired"
    # Every transaction eventually settled exactly once despite the kill.
    assert result.tx_committed + result.tx_rejected == 30
    assert result.tx_committed >= 29  # rejects only if a retry raced itself
