"""Native decode core vs the pure-Python decoder: bit-for-bit conformance.

The C decoder (corda_tpu/native/_ccodec.c) must accept exactly what the
Python decoder accepts (same values) and reject exactly what it rejects
(DeserializationError both sides) — on round-tripped values AND on
adversarial mutated byte strings.
"""

import random

import pytest

from corda_tpu.serialization import codec

pytestmark = pytest.mark.skipif(
    not codec._load_native(), reason="native codec unavailable (no gcc?)")


def _decode_py(raw: bytes):
    value, pos = codec._decode(raw, 0)
    if pos != len(raw):
        raise codec.DeserializationError("trailing")
    return value


def _decode_c(raw: bytes):
    return codec._ccodec.decode(raw)


def _corpus():
    from corda_tpu.crypto.hashes import SecureHash
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.testing.dummies import DummyContract

    kp = KeyPair.generate(b"\x42" * 32)
    notary_kp = KeyPair.generate(b"\x43" * 32)
    from corda_tpu.crypto.party import Party

    party = Party.of("P", kp.public)
    notary = Party.of("N", notary_kp.public)
    builder = DummyContract.generate_initial(party.ref(b"\x01"), 7, notary)
    builder.sign_with(kp)
    stx = builder.to_signed_transaction(check_sufficient_signatures=False)
    return [
        None, True, False, 0, 1, -1, 63, 64, -64, -65, 2**63, -(2**63),
        2**255 - 19, -(2**200), 0.0, 1.5, -2.25, 1e300,
        b"", b"\x00" * 33, "", "ascii", "unié中",
        (), (1, (2, (3, (4,)))), {"a": 1, "zz": {"n": ()}},
        frozenset(), frozenset({1, "x", b"y"}),
        SecureHash.sha256(b"leaf"), party, stx,
    ]


def _encode_py(v) -> bytes:
    out = bytearray()
    codec._encode(out, v)
    return bytes(out)


def test_values_agree():
    for v in _corpus():
        # Encode parity must hold BYTE-FOR-BYTE (not merely "both decoders
        # accept it"): encoded bytes feed Merkle ids, so a native/pure
        # divergence would split tx identity between nodes.
        c_raw = codec._ccodec.encode(v)
        # memoized types cache their encoding on first serialize; clear so
        # the pure encoder genuinely re-encodes rather than splicing the
        # native bytes back.
        if getattr(v, "_codec_enc", None) is not None:
            object.__setattr__(v, "_codec_enc", None)
        py_raw = _encode_py(v)
        assert c_raw == py_raw, type(v)
        assert _decode_c(c_raw) == _decode_py(c_raw) == v


def test_mutation_fuzz_agreement():
    # Mutate real encodings; the two decoders must agree on accept/reject
    # AND on the decoded value when both accept.
    rng = random.Random(11)
    corpus = [codec.serialize(v).bytes for v in _corpus()]
    checked = 0
    for raw in corpus:
        for _ in range(40):
            buf = bytearray(raw)
            op = rng.randrange(3)
            if op == 0 and buf:
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            elif op == 1 and len(buf) > 1:
                del buf[rng.randrange(len(buf))]
            else:
                buf.insert(rng.randrange(len(buf) + 1), rng.randrange(256))
            mutated = bytes(buf)
            try:
                py_val = _decode_py(mutated)
                py_err = None
            except codec.DeserializationError:
                py_val, py_err = None, True
            try:
                c_val = _decode_c(mutated)
                c_err = None
            except codec.DeserializationError:
                c_val, c_err = None, True
            assert py_err == c_err, mutated.hex()
            if py_err is None:
                assert py_val == c_val, mutated.hex()
            checked += 1
    assert checked >= 1000


def test_truncation_sweep_agreement():
    for v in _corpus():
        raw = codec.serialize(v).bytes
        for cut in range(len(raw)):
            prefix = raw[:cut]
            with pytest.raises(codec.DeserializationError):
                _decode_py(prefix)
            with pytest.raises(codec.DeserializationError):
                _decode_c(prefix)


def test_deep_nesting_rejected_both():
    raw = codec.serialize(1).bytes
    for _ in range(70):  # > _MAX_DEPTH
        raw = bytes([0x06, 0x01]) + raw  # list of one
    with pytest.raises(codec.DeserializationError, match="deep"):
        _decode_py(raw)
    with pytest.raises(codec.DeserializationError, match="deep"):
        _decode_c(raw)
