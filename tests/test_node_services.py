"""Node-tier services: RPC, scheduler, vault rebuild, progress tracking.

Mirrors the reference's coverage of CordaRPCOps/RPCUserService (reference:
node/.../messaging/CordaRPCOps.kt:62-117, RPCUserService.kt),
NodeSchedulerServiceTest (node/.../events/NodeSchedulerService.kt:45-70) and
ProgressTracker (core/.../utilities/ProgressTracker.kt:35).
"""

import time
from dataclasses import dataclass

import pytest

from corda_tpu.contracts.structures import (
    Contract,
    SchedulableState,
    now_micros,
)
from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.flows.api import FlowLogic, register_flow
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.node.rpc import RpcClient, RpcError
from corda_tpu.node.services.scheduler import ScheduledActivity
from corda_tpu.serialization.codec import register
from corda_tpu.utils.progress import Change, ProgressTracker, Step

import os
import sys
sys.path.insert(0, os.path.dirname(__file__))
from test_tcp_node import issue_and_move, pump_until  # noqa: E402


RPC_USERS = ({"username": "demo", "password": "s3cret",
              "permissions": ["ALL"]},
             {"username": "limited", "password": "pw", "permissions": []})


@register_flow
class PingFlow(FlowLogic):
    """Trivial whitelisted flow for RPC start tests."""

    def __init__(self, payload: str):
        self.payload = payload

    def call(self):
        return f"pong:{self.payload}"


class TestRpc:
    def _node(self, tmp_path):
        return Node(NodeConfig(
            name="RpcNode", base_dir=tmp_path / "RpcNode",
            network_map=tmp_path / "netmap.json",
            rpc_users=RPC_USERS)).start()

    def test_auth_and_start_flow(self, tmp_path):
        import threading

        node = self._node(tmp_path)
        client = RpcClient(node.messaging.my_address, "demo", "s3cret")
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                node.run_once(timeout=0.01)

        pumper = threading.Thread(target=pump, daemon=True)
        pumper.start()
        try:
            handle = client.start_flow("PingFlow", "hello")
            value = client.wait_for_flow(handle)
            assert value == "pong:hello"
            assert client.call("node_identity") == node.identity
        finally:
            stop.set()
            pumper.join(timeout=2)
            client.close()
            node.stop()

    def test_bad_password_rejected(self, tmp_path):
        node = self._node(tmp_path)
        client = RpcClient(node.messaging.my_address, "demo", "WRONG",
                           timeout=5.0)
        try:
            import threading
            pumper = threading.Thread(
                target=lambda: [node.run_once(timeout=0.01)
                                for _ in range(300)], daemon=True)
            pumper.start()
            with pytest.raises(RpcError, match="authentication"):
                client.call("vault_snapshot")
        finally:
            client.close()
            node.stop()

    def test_permissions_gate_flow_start(self, tmp_path):
        node = self._node(tmp_path)
        client = RpcClient(node.messaging.my_address, "limited", "pw",
                           timeout=5.0)
        try:
            import threading
            pumper = threading.Thread(
                target=lambda: [node.run_once(timeout=0.01)
                                for _ in range(300)], daemon=True)
            pumper.start()
            with pytest.raises(RpcError, match="may not start"):
                client.start_flow("PingFlow", "x")
        finally:
            client.close()
            node.stop()

    def test_arbitrary_attributes_not_dispatchable(self, tmp_path):
        node = self._node(tmp_path)
        client = RpcClient(node.messaging.my_address, "demo", "s3cret",
                           timeout=5.0)
        try:
            import threading
            pumper = threading.Thread(
                target=lambda: [node.run_once(timeout=0.01)
                                for _ in range(300)], daemon=True)
            pumper.start()
            with pytest.raises(RpcError, match="no such method"):
                client.call("_handle")
            with pytest.raises(RpcError, match="no such method"):
                client.call("__init__")
        finally:
            client.close()
            node.stop()


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


FIRED: list[str] = []


@register_flow
class ScheduledPing(FlowLogic):
    def __init__(self, tag: str):
        self.tag = tag

    def call(self):
        FIRED.append(self.tag)
        return self.tag


class _AcceptAll(Contract):
    def verify(self, tx):
        pass

    @property
    def legal_contract_reference(self):
        return SecureHash.sha256(b"accept-all")


@register
@dataclass(frozen=True)
class TimerState(SchedulableState):
    """A state that asks for ScheduledPing at `fire_at`."""

    owner_tag: str = ""
    fire_at: int = 0
    owner = None  # set per-test: vault relevancy needs a participant

    @property
    def contract(self):
        return _AcceptAll()

    @property
    def participants(self):
        return [TimerState.owner] if TimerState.owner is not None else []

    def next_scheduled_activity(self, this_state_ref, flow_factory):
        return ScheduledActivity("ScheduledPing", (self.owner_tag,),
                                 self.fire_at)


def test_scheduler_fires_due_state(tmp_path):
    from corda_tpu.contracts.structures import Command, TypeOnlyCommandData
    from corda_tpu.transactions.builder import TransactionBuilder

    node = Node(NodeConfig(name="Sched", base_dir=tmp_path / "Sched",
                           network_map=tmp_path / "netmap.json")).start()
    try:
        FIRED.clear()

        @register
        @dataclass(frozen=True)
        class _Noop(TypeOnlyCommandData):
            pass

        fire_at = now_micros() + 100_000  # 0.1s from now
        TimerState.owner = node.identity.owning_key  # vault relevancy
        tx = TransactionBuilder(notary=node.identity)
        tx.add_output_state(TimerState("tick-1", fire_at))
        tx.add_command(Command(_Noop(), (node.identity.owning_key,)))
        tx.sign_with(node.key)
        stx = tx.to_signed_transaction()
        node.services.record_transactions([stx])

        assert node.scheduler.next_scheduled is not None
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not FIRED:
            node.run_once(timeout=0.01)
        assert FIRED == ["tick-1"]
        assert node.scheduler.next_scheduled is None  # consumed
    finally:
        node.stop()


def test_vault_rebuilds_after_restart(tmp_path):
    node = Node(NodeConfig(name="V", base_dir=tmp_path / "V",
                           network_map=tmp_path / "netmap.json")).start()
    stx = issue_and_move(node, node.identity, magic=5)
    node.services.record_transactions([stx])
    before = {s.ref for s in node.services.vault_service.current_vault.states}
    assert before
    node.stop()
    del node

    reborn = Node(NodeConfig(name="V", base_dir=tmp_path / "V",
                             network_map=tmp_path / "netmap.json")).start()
    try:
        after = {s.ref
                 for s in reborn.services.vault_service.current_vault.states}
        assert after == before
    finally:
        reborn.stop()


# ---------------------------------------------------------------------------
# ProgressTracker
# ---------------------------------------------------------------------------


def test_progress_tracker_stream_and_children():
    fetching = Step("Fetching")
    verifying = Step("Verifying")
    signing = Step("Signing")
    tracker = ProgressTracker(fetching, verifying, signing)
    seen: list[tuple[str, ...]] = []
    tracker.subscribe(lambda c: seen.append(c.path))

    tracker.next_step()
    assert tracker.current_step == fetching
    child = ProgressTracker(Step("Downloading"), Step("Checking"))
    tracker.set_child_tracker(verifying, child)
    tracker.next_step()
    child.next_step()  # bubbles through the parent path
    tracker.current_step = signing
    from corda_tpu.utils.progress import DONE

    tracker.current_step = DONE
    assert seen == [
        ("Fetching",),
        ("Verifying",),
        ("Verifying", "Downloading"),
        ("Signing",),
        ("Done",),
    ]


# ---------------------------------------------------------------------------
# Network map directory service (wire tier)
# ---------------------------------------------------------------------------


def test_netmap_service_register_fetch_subscribe(tmp_path):
    """A map node serves signed registrations; late joiners learn earlier
    nodes over the wire (not from the bootstrap file), and registrations not
    signed by the registering identity are rejected."""
    map_node = Node(NodeConfig(
        name="MapNode", base_dir=tmp_path / "MapNode",
        network_map=tmp_path / "netmap.json", map_service=True)).start()
    a = Node(NodeConfig(
        name="NodeA", base_dir=tmp_path / "NodeA",
        network_map=tmp_path / "netmap.json", map_node="MapNode")).start()
    nodes = [map_node, a]
    try:
        pump_until(nodes, lambda: a.netmap_client.registered
                   and a.netmap_client.fetched)
        assert map_node.netmap_service.node_count == 1

        # NodeB never touches the bootstrap file after boot; it learns NodeA
        # through fetch, and NodeA learns NodeB through the pushed update.
        b = Node(NodeConfig(
            name="NodeB", base_dir=tmp_path / "NodeB",
            network_map=tmp_path / "netmap.json", map_node="MapNode")).start()
        nodes.append(b)
        pump_until(nodes, lambda: b.netmap_client.registered)
        pump_until(nodes, lambda: any(
            n.legal_identity.name == "NodeA"
            for n in b.network_map_cache.party_nodes))
        pump_until(nodes, lambda: any(
            n.legal_identity.name == "NodeB"
            for n in a.network_map_cache.party_nodes))

        # Forged registration: NodeB signs a registration claiming NodeA's
        # identity but pointing at B's OWN address (session hijack attempt).
        # Rejected: the map's entry and serial for NodeA must not change.
        from dataclasses import replace as _replace

        from corda_tpu.node.services.netmap_service import (
            ADD, NodeRegistration, RegistrationRequest,
        )
        from corda_tpu.crypto.signed_data import SignedData
        from corda_tpu.serialization.codec import serialize
        from corda_tpu.node.messaging.api import TopicSession

        serial_before = map_node.netmap_service.serial_of("NodeA")
        forged_info = _replace(a.info, address=b.messaging.my_address)
        reg = NodeRegistration(forged_info, 999, ADD)
        blob = serialize(reg)
        signed = SignedData(blob, b.key.sign(blob.bytes))  # B signs as A
        b.messaging.send(
            TopicSession("platform.netmap", 0),
            serialize(RegistrationRequest(
                signed, b.messaging.my_address)).bytes,
            map_node.messaging.my_address)
        for _ in range(30):
            for n in nodes:
                n.run_once(timeout=0.005)
        stored = map_node.netmap_service.get_node("NodeA")
        assert stored is not None
        assert stored.address == a.messaging.my_address  # NOT hijacked
        assert map_node.netmap_service.serial_of("NodeA") == serial_before
        # A legitimate re-register (next serial) still succeeds.
        a.netmap_client.register(a.info)
        pump_until(nodes, lambda:
                   map_node.netmap_service.serial_of("NodeA")
                   == serial_before + 1)
    finally:
        for n in nodes:
            n.stop()


def test_notarisation_emits_progress_events(tmp_path):
    """The library flows declare real progress steps: a notarisation's
    change feed shows the NotaryClientFlow tracker advancing (the stream the
    reference renders over RPC/console)."""
    from corda_tpu.flows.notary import NotaryClientFlow
    from test_tcp_node import issue_and_move, pump_until

    notary = Node(NodeConfig(name="Notary", base_dir=tmp_path / "Notary",
                             notary="simple",
                             network_map=tmp_path / "m.json")).start()
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "m.json")).start()
    try:
        for n in (notary, alice):
            n.refresh_netmap()
        stx = issue_and_move(alice, notary.identity, magic=33)
        h = alice.start_flow(NotaryClientFlow(stx))
        pump_until([notary, alice], lambda: h.result.done)
        h.result.result()
        _cursor, events = alice.smm.changes.since(0)
        paths = [e[2] for e in events if e[0] == "progress"]
        labels = [p[-1] for p in paths]
        assert "Verifying our signatures" in labels
        assert "Requesting signature by notary service" in labels
        assert "Validating response from notary service" in labels
    finally:
        notary.stop()
        alice.stop()
