"""Obligation rules: issue, move, settle (full + partial), bilateral netting.

Mirrors the reference's ObligationTests (reference: finance/src/test/kotlin/
net/corda/contracts/asset/ObligationTests.kt) at the rules tier, via the
ledger DSL.
"""

import pytest

from corda_tpu.contracts.structures import Issued
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.finance import Amount, CashState
from corda_tpu.finance.cash import CashMove
from corda_tpu.finance.obligation import (
    Obligation,
    ObligationIssue,
    ObligationMove,
    ObligationNet,
    ObligationSettle,
    ObligationState,
)
from corda_tpu.testing.ledger_dsl import ledger

ALICE = Party.of("Alice", KeyPair.generate(b"\x71" * 32).public)
BOB = Party.of("Bob", KeyPair.generate(b"\x72" * 32).public)
BANK = Party.of("Bank", KeyPair.generate(b"\x73" * 32).public)
NOTARY = Party.of("Notary", KeyPair.generate(b"\x74" * 32).public)

TOKEN = Issued(BANK.ref(b"\x01"), "USD")


def owed(obligor, owner, qty):
    return ObligationState(obligor.owning_key, Amount(qty, TOKEN),
                           owner.owning_key)


def cash(owner, qty):
    return CashState(Amount(qty, TOKEN), owner.owning_key)


def test_issue_and_full_settle():
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.output("iou", owed(ALICE, BOB, 1000))
        tx.command(ObligationIssue(1), ALICE.owning_key)
        tx.verifies()
    with l.transaction() as tx:
        tx.input("iou")
        tx.input(cash(ALICE, 1000))
        tx.output(cash(BOB, 1000))
        tx.command(ObligationSettle(Amount(1000, TOKEN)), ALICE.owning_key)
        tx.command(CashMove(), ALICE.owning_key)
        tx.verifies()


def test_partial_settle_leaves_remainder():
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 1000))
        tx.output(owed(ALICE, BOB, 400))  # remainder
        tx.input(cash(ALICE, 600))
        tx.output(cash(BOB, 600))
        tx.command(ObligationSettle(Amount(600, TOKEN)), ALICE.owning_key)
        tx.command(CashMove(), ALICE.owning_key)
        tx.verifies()


def test_settle_without_cash_rejected():
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 1000))
        tx.command(ObligationSettle(Amount(1000, TOKEN)), ALICE.owning_key)
        tx.fails_with("cash moves to each beneficiary")


def test_settle_underpayment_rejected():
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 1000))
        tx.input(cash(ALICE, 500))
        tx.output(cash(BOB, 500))  # only half, but claims full settlement
        tx.command(ObligationSettle(Amount(1000, TOKEN)), ALICE.owning_key)
        tx.command(CashMove(), ALICE.owning_key)
        tx.fails_with("cash moves to each beneficiary")


def test_move_reassigns_beneficiary_only():
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 1000))
        tx.output(owed(ALICE, BANK, 1000))  # Bob sells the IOU to the bank
        tx.command(ObligationMove(), BOB.owning_key)
        tx.verifies()
    with l.transaction() as tx:  # obligor cannot be swapped in a move
        tx.input(owed(ALICE, BOB, 1000))
        tx.output(owed(BANK, BOB, 1000))
        tx.command(ObligationMove(), BOB.owning_key)
        tx.fails_with("terms other than the beneficiary")


def test_bilateral_netting():
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 1000))
        tx.input(owed(BOB, ALICE, 300))
        tx.output(owed(ALICE, BOB, 700))  # net
        tx.command(ObligationNet(), ALICE.owning_key, BOB.owning_key)
        tx.verifies()
    with l.transaction() as tx:  # perfectly offsetting debts cancel
        tx.input(owed(ALICE, BOB, 500))
        tx.input(owed(BOB, ALICE, 500))
        tx.command(ObligationNet(), ALICE.owning_key, BOB.owning_key)
        tx.verifies()
    with l.transaction() as tx:  # wrong net amount rejected
        tx.input(owed(ALICE, BOB, 1000))
        tx.input(owed(BOB, ALICE, 300))
        tx.output(owed(ALICE, BOB, 900))
        tx.command(ObligationNet(), ALICE.owning_key, BOB.owning_key)
        tx.fails_with("right direction and size")
    with l.transaction() as tx:  # both signatures required
        tx.input(owed(ALICE, BOB, 1000))
        tx.input(owed(BOB, ALICE, 300))
        tx.output(owed(ALICE, BOB, 700))
        tx.command(ObligationNet(), ALICE.owning_key)
        tx.fails_with("both parties signed")


def test_generate_settle_roundtrip():
    """generate_settle builds a transaction the contract accepts."""
    from corda_tpu.contracts.structures import StateAndRef, StateRef, \
        TransactionState
    from corda_tpu.crypto.hashes import SecureHash
    from corda_tpu.transactions.builder import TransactionBuilder

    iou = StateAndRef(
        TransactionState(owed(ALICE, BOB, 1000), NOTARY),
        StateRef(SecureHash.sha256(b"iou"), 0))
    money = StateAndRef(
        TransactionState(cash(ALICE, 1500), NOTARY),
        StateRef(SecureHash.sha256(b"cash"), 0))
    tx = TransactionBuilder(notary=NOTARY)
    Obligation.generate_settle(tx, [iou], [money], Amount(600, TOKEN))
    l = ledger(NOTARY)
    with l.transaction() as t:
        # Re-run the built components through the DSL verifier.
        for out in tx.outputs:
            t.output(out.data)
        t.input(iou.state.data)
        t.input(money.state.data)
        for cmd in tx.commands:
            t.command(cmd.value, *cmd.signers)
        t.verifies()


def test_move_with_multiple_obligors_in_one_group():
    """Regression: moving obligations from DIFFERENT obligors (same token)
    must verify — the terms comparison needs a canonical key ordering, since
    composite keys define no natural order."""
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 100))
        tx.input(owed(BANK, BOB, 50))
        tx.output(owed(ALICE, NOTARY, 100))  # both IOUs move to a new owner
        tx.output(owed(BANK, NOTARY, 50))
        tx.command(ObligationMove(), BOB.owning_key)
        tx.verifies()


def test_settle_cannot_reassign_remainder_obligor():
    """Regression: the settle remainder must keep the original obligor — a
    settlement cannot transfer leftover debt to a party who never signed."""
    EVE = Party.of("Eve", KeyPair.generate(b"\x75" * 32).public)
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.input(owed(ALICE, BOB, 1000))
        tx.output(owed(EVE, BOB, 400))  # debt shoved onto Eve
        tx.input(cash(ALICE, 600))
        tx.output(cash(BOB, 600))
        tx.command(ObligationSettle(Amount(600, TOKEN)), ALICE.owning_key)
        tx.command(CashMove(), ALICE.owning_key)
        tx.fails_with("original obligor")


def test_net_command_does_not_hijack_unrelated_group():
    """Regression: a move group in the same tx as a netting must still be
    verified as a move (per-group dispatch, not tx-wide)."""
    OTHER = Issued(ALICE.ref(b"\x02"), "GBP")

    def owed_gbp(obligor, owner, qty):
        return ObligationState(obligor.owning_key, Amount(qty, OTHER),
                               owner.owning_key)

    l = ledger(NOTARY)
    with l.transaction() as tx:
        # Group 1 (USD): a real bilateral netting.
        tx.input(owed(ALICE, BOB, 1000))
        tx.input(owed(BOB, ALICE, 300))
        tx.output(owed(ALICE, BOB, 700))
        tx.command(ObligationNet(), ALICE.owning_key, BOB.owning_key)
        # Group 2 (GBP): two obligations simply moving to a new owner.
        tx.input(owed_gbp(ALICE, BOB, 100))
        tx.input(owed_gbp(BANK, BOB, 50))
        tx.output(owed_gbp(ALICE, NOTARY, 100))
        tx.output(owed_gbp(BANK, NOTARY, 50))
        tx.command(ObligationMove(), BOB.owning_key)
        tx.verifies()


def test_generate_settle_rejects_mixed_pairs():
    from corda_tpu.contracts.structures import StateAndRef, StateRef, \
        TransactionState
    from corda_tpu.crypto.hashes import SecureHash
    from corda_tpu.transactions.builder import TransactionBuilder

    iou1 = StateAndRef(TransactionState(owed(ALICE, BOB, 500), NOTARY),
                       StateRef(SecureHash.sha256(b"a"), 0))
    iou2 = StateAndRef(TransactionState(owed(BANK, BOB, 500), NOTARY),
                       StateRef(SecureHash.sha256(b"b"), 0))
    tx = TransactionBuilder(notary=NOTARY)
    with pytest.raises(ValueError, match="single .obligor, beneficiary."):
        Obligation.generate_settle(tx, [iou1, iou2], [], Amount(600, TOKEN))
