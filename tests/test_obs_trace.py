"""The cross-node tracing subsystem (corda_tpu/obs/).

Covers the ISSUE acceptance list: the stitched trace over the in-memory
network (one trace_id from the client flow through the responder notary
flow, correct span parentage), the raft commit-path spans over a real TCP
cluster, device-batch fan-in (one batch span carries every member flow's
trace id), the disarmed-path overhead guard (one attribute check, no span
allocation, no envelope growth), the merged Chrome trace + stage breakdown
collectors, and the satellite metrics-history / transport-stats surfaces.
"""

import json
import urllib.request
from collections import deque

import pytest

from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.flows.notary import NotaryClientFlow
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.obs import collect, trace as obs
from corda_tpu.testing import DummyContract
from corda_tpu.testing.mock_network import MockNetwork

import sys
import os
sys.path.insert(0, os.path.dirname(__file__))
from test_tcp_node import issue_and_move, pump_until  # noqa: E402


@pytest.fixture()
def recorder():
    rec = obs.arm("test", capacity=4096)
    yield rec
    obs.disarm()


@pytest.fixture()
def net():
    network = MockNetwork(verifier=CpuVerifier())
    yield network
    network.stop_nodes()


def _notarise_move(net):
    notary = net.create_notary_node("Notary")
    alice = net.create_node("Alice")
    bob = net.create_node("Bob")
    builder = DummyContract.generate_initial(
        alice.identity.ref(b"\x00"), 7, notary.identity)
    builder.sign_with(alice.key)
    issue_stx = builder.to_signed_transaction()
    alice.record_transaction(issue_stx)
    move = DummyContract.move(issue_stx.tx.out_ref(0),
                              bob.identity.owning_key)
    move.sign_with(alice.key)
    move_stx = move.to_signed_transaction(check_sufficient_signatures=False)
    handle = alice.start_flow(NotaryClientFlow(move_stx))
    net.run_network()
    assert handle.result.done and handle.result.exception() is None
    return handle


# ---------------------------------------------------------------------------
# Recorder unit behaviour
# ---------------------------------------------------------------------------


def test_ring_overwrites_oldest_and_counts_drops():
    rec = obs.SpanRecorder("n", capacity=4)
    for i in range(6):
        rec.record("s", float(i), float(i) + 0.5)
    snap = rec.snapshot()
    assert [s["t_start"] for s in snap] == [2.0, 3.0, 4.0, 5.0]
    stats = rec.stats()
    assert stats["recorded"] == 6
    assert stats["buffered"] == 4
    assert stats["dropped"] == 2


def test_link_map_is_bounded():
    rec = obs.SpanRecorder("n", capacity=4)
    for i in range(obs.LINK_MAP_MAX + 5):
        rec.register_link(i.to_bytes(8, "big"), b"t" * 8, b"s" * 8)
    # Wholesale clear at the cap: correlation loss beats unbounded growth.
    assert len(rec._links) <= obs.LINK_MAP_MAX


def test_arm_from_env_parses_capacity(monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, "128")
    try:
        rec = obs.arm_from_env("envnode")
        assert rec is not None and rec.capacity == 128
        monkeypatch.setenv(obs.ENV_VAR, "on")
        rec = obs.arm_from_env("envnode")
        assert rec is not None and rec.capacity == obs.DEFAULT_CAPACITY
        monkeypatch.setenv(obs.ENV_VAR, "nonsense")
        assert obs.arm_from_env("envnode") is None
    finally:
        obs.disarm()


# ---------------------------------------------------------------------------
# Stitched trace over the in-memory network
# ---------------------------------------------------------------------------


def test_inmem_notarise_stitches_one_trace(recorder, net):
    _notarise_move(net)
    spans = recorder.snapshot()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    client = by_name["flow:NotaryClientFlow"]
    assert len(client) == 1
    root = client[0]
    assert root["parent"] is None
    trace_id = root["trace_id"]

    # The responder flow inherited the client's trace over Message.trace
    # and parents to the client's root span.
    service = [s for s in spans
               if s["name"] == "flow:ValidatingNotaryFlow"
               and s["trace_id"] == trace_id]
    assert len(service) == 1
    assert service[0]["parent"] == root["span_id"]

    # The notary-side processing span parents to the responder flow.
    proc = [s for s in by_name.get("notary_process", ())
            if s["trace_id"] == trace_id]
    assert len(proc) == 1
    assert proc[0]["parent"] == service[0]["span_id"]
    assert proc[0]["attrs"]["ok"] is True

    # Every recorded span for this transaction shares ONE trace id.
    tx_spans = [s for s in spans if s["trace_id"] == trace_id]
    assert len(tx_spans) >= 3
    # And the stages nest inside the root's wall time (small slack for the
    # epoch re-anchoring of perf-counter durations).
    for s in tx_spans:
        assert s["t_end"] <= root["t_end"] + 0.05


def test_stage_breakdown_from_inmem_trace(recorder, net):
    _notarise_move(net)
    snap = {"node": "inproc", "spans": recorder.snapshot()}
    breakdown = collect.stage_breakdown([snap])
    assert breakdown["traces"] >= 1
    assert set(breakdown["stages"]) == set(collect.STAGES)
    e2e = breakdown["end_to_end"]["mean_ms"]
    assert e2e > 0
    # The derived reply stage closes the attribution gap: stage sum tracks
    # end-to-end by construction.
    total = sum(v["mean_ms"] for v in breakdown["stages"].values())
    assert total <= e2e * 1.05


def test_merged_chrome_trace_shape(recorder, net, tmp_path):
    _notarise_move(net)
    path = tmp_path / "trace.json"
    collect.write_chrome_trace(str(path), [
        {"node": "inproc", "spans": recorder.snapshot()}])
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert "flow:NotaryClientFlow" in names
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in events)
    for e in events:
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] > 0


# ---------------------------------------------------------------------------
# Raft commit-path spans over a real TCP cluster
# ---------------------------------------------------------------------------


def test_raft_cluster_commit_spans(recorder, tmp_path):
    cluster = ("RaftA", "RaftB", "RaftC")
    nodes = []
    for name in cluster:
        nodes.append(Node(NodeConfig(
            name=name, base_dir=tmp_path / name, notary="raft-simple",
            raft_cluster=cluster,
            network_map=tmp_path / "netmap.json")).start())
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "netmap.json")).start()
    everyone = nodes + [alice]
    try:
        import time as _time
        deadline = _time.monotonic() + 15.0
        leader = None
        while _time.monotonic() < deadline and leader is None:
            for n in everyone:
                n.run_once(timeout=0.005)
            leader = next((n for n in nodes
                           if n.raft_member.role == "leader"), None)
        assert leader is not None, "no leader elected"
        for n in everyone:
            n.refresh_netmap()

        stx = issue_and_move(alice, leader.identity, magic=1)
        h = alice.start_flow(NotaryClientFlow(stx))
        pump_until(everyone, lambda: h.result.done)
        assert h.result.exception() is None

        spans = recorder.snapshot()
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)

        roots = [s for s in by_name.get("flow:NotaryClientFlow", ())
                 if s["parent"] is None]
        assert len(roots) == 1
        trace_hex = roots[0]["trace_id"]

        # The per-transaction commit span from the flow's point of view.
        commits = [s for s in by_name.get("raft_commit", ())
                   if s["trace_id"] == trace_hex]
        assert len(commits) == 1 and commits[0]["attrs"]["ok"] is True

        # The batch-level consensus spans fan IN: member_traces carries
        # this transaction's trace id through append/fsync/replication.
        for stage in ("raft_append", "fsync", "replication"):
            attributed = [
                s for s in by_name.get(stage, ())
                if trace_hex in (s["attrs"].get("member_traces") or ())]
            assert attributed, f"no {stage} span attributed to the trace"
    finally:
        for n in everyone:
            n.stop()


# ---------------------------------------------------------------------------
# Device-batch fan-in from the feeder thread
# ---------------------------------------------------------------------------


def test_feeder_batch_spans_carry_member_traces(recorder):
    from corda_tpu.crypto.async_verify import AsyncVerifyService
    from corda_tpu.crypto.provider import VerifyJob

    class _OkVerifier:
        name = "stub-ok"

        def verify_batch(self, jobs):
            return [True] * len(jobs)

    class _Fsm:
        def __init__(self):
            self.trace_id = obs.new_trace_id()

    fsms = [_Fsm(), _Fsm()]
    svc = AsyncVerifyService(_OkVerifier(), depth=2, adaptive=False)
    jobs = [VerifyJob(pubkey=b"\x00" * 32, message=b"\x01" * 32,
                     sig=b"\x02" * 64) for _ in range(2)]
    try:
        svc.submit(jobs, [(fsm, None) for fsm in fsms])
        import time as _time
        deadline = _time.monotonic() + 10.0
        done = []
        while not done and _time.monotonic() < deadline:
            done = svc.drain()
            _time.sleep(0.002)
        assert done, "batch never completed"
    finally:
        svc.close()

    spans = {s["name"]: s for s in recorder.snapshot()}
    for stage in ("queue_wait", "device_verify"):
        assert stage in spans, f"missing {stage} span"
        members = spans[stage]["attrs"]["member_traces"]
        assert sorted(members) == sorted(f.trace_id.hex() for f in fsms)
        assert spans[stage]["attrs"]["sigs"] == 2


# ---------------------------------------------------------------------------
# Overhead guard: the disarmed path is one attribute check
# ---------------------------------------------------------------------------


def test_disarmed_path_allocates_nothing(net, monkeypatch):
    assert obs.ACTIVE is None

    def _boom(*a, **kw):  # any span/id allocation while disarmed is a bug
        raise AssertionError("tracing touched while disarmed")

    monkeypatch.setattr(obs, "new_trace_id", _boom)
    monkeypatch.setattr(obs, "new_span_id", _boom)
    monkeypatch.setattr(obs.SpanRecorder, "record", _boom)
    _notarise_move(net)
    # No envelope growth either: every message crossed with trace=None.
    assert net.messaging_network.sent_messages
    assert all(m.message.trace is None
               for m in net.messaging_network.sent_messages)


def test_tcp_wire_tuple_width_gated_on_arming():
    from types import SimpleNamespace

    from corda_tpu.node.messaging.api import TopicSession
    from corda_tpu.node.messaging.tcp import TcpMessaging

    fake = SimpleNamespace(
        my_address=SimpleNamespace(host="127.0.0.1", port=12345))
    ts = TopicSession("t", 0)
    assert obs.ACTIVE is None
    assert len(TcpMessaging._wire_tuple(fake, ts, b"u" * 8, b"d")) == 7
    obs.arm("wire")
    try:
        obs.clear_context()
        # Armed but no context on this thread: still the 7-field frame.
        assert len(TcpMessaging._wire_tuple(fake, ts, b"u" * 8, b"d")) == 7
        obs.set_context(b"t" * 8, b"s" * 8)
        wide = TcpMessaging._wire_tuple(fake, ts, b"u" * 8, b"d")
        assert len(wide) == 9 and wide[7] == b"t" * 8 and wide[8] == b"s" * 8
    finally:
        obs.disarm()


# ---------------------------------------------------------------------------
# Satellites: metrics history deque + web surfaces + inmem transport stats
# ---------------------------------------------------------------------------


def test_metrics_history_is_bounded_deque_and_served(tmp_path):
    node = Node(NodeConfig(name="WebNode", base_dir=tmp_path / "WebNode",
                           network_map=tmp_path / "netmap.json",
                           web_port=0)).start()
    try:
        assert isinstance(node.metrics_history, deque)
        assert node.metrics_history.maxlen == Node.METRICS_HISTORY_KEEP
        for i in range(Node.METRICS_HISTORY_KEEP + 10):
            node.metrics_history.append({"t": i})
        assert len(node.metrics_history) == Node.METRICS_HISTORY_KEEP
        assert node.metrics_history[0] == {"t": 10}  # oldest self-trimmed

        base = f"http://127.0.0.1:{node.webserver.port}"
        with urllib.request.urlopen(f"{base}/api/metrics/history",
                                    timeout=5.0) as resp:
            history = json.load(resp)
        assert isinstance(history, list)
        assert len(history) == Node.METRICS_HISTORY_KEEP
        # Served newest-first: dashboards and flight-dump readers want the
        # most recent sample at index 0 (the deque itself stays
        # oldest-first append order).
        assert history[0] == {"t": Node.METRICS_HISTORY_KEEP + 9}
        assert history[-1] == {"t": 10}
    finally:
        node.stop()


def test_api_trace_serves_span_buffer(tmp_path):
    node = Node(NodeConfig(name="TraceNode", base_dir=tmp_path / "TraceNode",
                           network_map=tmp_path / "netmap.json",
                           web_port=0)).start()
    try:
        base = f"http://127.0.0.1:{node.webserver.port}"
        with urllib.request.urlopen(f"{base}/api/trace",
                                    timeout=5.0) as resp:
            disarmed = json.load(resp)
        assert disarmed == {"node": "TraceNode", "armed": False,
                            "spans": [], "stats": None}
        rec = obs.arm("TraceNode", capacity=16)
        try:
            rec.record("demo", 1.0, 2.0)
            with urllib.request.urlopen(f"{base}/api/trace",
                                        timeout=5.0) as resp:
                armed = json.load(resp)
        finally:
            obs.disarm()
        assert armed["armed"] is True
        assert [s["name"] for s in armed["spans"]] == ["demo"]
        assert armed["stats"]["recorded"] == 1
    finally:
        node.stop()


def test_inmem_transport_stats_schema_parity(net):
    from corda_tpu.node.messaging.tcp import TcpMessaging

    node = net.create_node("StatsNode")
    stats = node.messaging.transport_stats()
    expected = {
        "outbox_appends", "outbox_bursts", "outbox_burst_frames",
        "outbox_max_burst", "outbox_burst_avg", "bridge_flushes",
        "bridge_flush_frames", "bridge_max_flush", "bridge_flush_avg",
        "redeliveries", "stale_resends", "poison_pending", "poison_drops",
        "poison_retry_limit", "frames_sent_total",
    }
    assert set(stats) == expected
    assert stats["redeliveries"] == 0
    # Real parity, not just the inmem side of it: a TcpMessaging instance
    # (not started: no sockets, just counter state) must expose the exact
    # same key set, so cluster collectors can merge stats without
    # per-transport special cases.
    tcp_stats = TcpMessaging().transport_stats()
    assert set(tcp_stats) == set(stats) == expected
