"""Oracle flows: query a fix, get a signature over a Merkle tear-off.

Mirrors the reference's NodeInterestRatesTest + oracle privacy property
(reference: samples/irs-demo/src/test/kotlin/net/corda/irs/api/
NodeInterestRatesTest.kt; oracle at NodeInterestRates.kt:37-55): the oracle
signs only when the revealed commands match its table, never sees other
components, and a tampered tear-off is rejected.
"""

import pytest

from corda_tpu.contracts.structures import Command
from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.flows.api import FlowException
from corda_tpu.flows.oracle import (
    Fix,
    FixOf,
    RateOracle,
    RatesFixQueryFlow,
    RatesFixSignFlow,
)
from corda_tpu.testing.dummies import DummyContract
from corda_tpu.testing.mock_network import MockNetwork


LIBOR_3M = FixOf("LIBOR", 20_000, "3M")
RATE = 5_6700  # 5.67% scaled by 10^4


def _setup():
    net = MockNetwork(verifier=CpuVerifier())
    notary = net.create_notary_node("Notary")
    oracle_node = net.create_node("Oracle Inc")
    alice = net.create_node("Alice")
    oracle = RateOracle(oracle_node.smm, oracle_node.key, {LIBOR_3M: RATE})
    return net, notary, oracle_node, alice, oracle


def _fixed_tx(alice, notary, fix: Fix):
    """A transaction carrying the fix as a command (plus a dummy state)."""
    builder = DummyContract.generate_initial(
        alice.identity.ref(b"\x01"), 5, notary.identity)
    builder.add_command(Command(fix, (alice.identity.owning_key,)))
    builder.sign_with(alice.key)
    return builder.to_signed_transaction(check_sufficient_signatures=False)


def test_query_then_sign_over_tear_off():
    net, notary, oracle_node, alice, oracle = _setup()
    try:
        qh = alice.start_flow(RatesFixQueryFlow(oracle_node.identity, LIBOR_3M))
        net.run_network()
        fix = qh.result.result()
        assert fix == Fix(LIBOR_3M, RATE)

        stx = _fixed_tx(alice, notary, fix)
        sh = alice.start_flow(RatesFixSignFlow(oracle_node.identity, stx))
        net.run_network()
        sig = sh.result.result()
        sig.verify(stx.id.bytes)
        assert sig.by == oracle_node.key.public
    finally:
        net.stop_nodes()


def test_oracle_rejects_wrong_fix_value():
    net, notary, oracle_node, alice, oracle = _setup()
    try:
        bad_fix = Fix(LIBOR_3M, RATE + 1)  # not what the oracle published
        stx = _fixed_tx(alice, notary, bad_fix)
        sh = alice.start_flow(RatesFixSignFlow(oracle_node.identity, stx))
        net.run_network()
        with pytest.raises(Exception, match="incorrect fix"):
            sh.result.result()
    finally:
        net.stop_nodes()


def test_oracle_rejects_unknown_fix_query():
    net, notary, oracle_node, alice, oracle = _setup()
    try:
        qh = alice.start_flow(RatesFixQueryFlow(
            oracle_node.identity, FixOf("EURIBOR", 20_000, "6M")))
        net.run_network()
        with pytest.raises(Exception, match="unknown fix"):
            qh.result.result()
    finally:
        net.stop_nodes()


def test_oracle_privacy_only_commands_revealed():
    """The tear-off the oracle receives contains ONLY the Fix commands: a
    client revealing outputs gets refused."""
    from corda_tpu.transactions.filtered import (
        FilteredTransaction,
        FilterFuns,
    )

    net, notary, oracle_node, alice, oracle = _setup()
    try:
        fix = Fix(LIBOR_3M, RATE)
        stx = _fixed_tx(alice, notary, fix)
        leaky = FilteredTransaction.build_merkle_transaction(
            stx.tx, FilterFuns(
                filter_commands=lambda c: isinstance(c.value, Fix),
                filter_outputs=lambda _o: True))  # oversharing
        with pytest.raises(FlowException, match="only see commands"):
            oracle.sign(leaky, stx.id)

        # And a proof against the WRONG id fails.
        proper = FilteredTransaction.build_merkle_transaction(
            stx.tx, FilterFuns(
                filter_commands=lambda c: isinstance(c.value, Fix)))
        from corda_tpu.crypto.hashes import SecureHash

        with pytest.raises(FlowException, match="Merkle proof"):
            oracle.sign(proper, SecureHash.zero())
    finally:
        net.stop_nodes()


class TestTwoPartyDeal:
    def test_deal_agreed_signed_and_finalised(self):
        """TwoPartyDealFlow capability (TwoPartyDealFlow.kt): instigator
        proposes, acceptor validates terms and signs, finality notarises and
        both record the deal."""
        from dataclasses import dataclass, field

        from corda_tpu.contracts.structures import (
            Contract,
            DealState,
            TypeOnlyCommandData,
            UniqueIdentifier,
        )
        from corda_tpu.crypto.hashes import SecureHash
        from corda_tpu.crypto.party import Party
        from corda_tpu.flows.deal import DealAcceptorFlow, DealInstigatorFlow
        from corda_tpu.serialization.codec import register

        @register
        @dataclass(frozen=True)
        class SwapCommand(TypeOnlyCommandData):
            pass

        class _SwapContract(Contract):
            def verify(self, tx):
                pass

            @property
            def legal_contract_reference(self):
                return SecureHash.sha256(b"swap")

        @register
        @dataclass(frozen=True)
        class SwapDeal(DealState):
            party_a: Party = None
            party_b: Party = None
            notional: int = 0
            uid: UniqueIdentifier = field(default_factory=UniqueIdentifier)

            @property
            def linear_id(self):
                return self.uid

            @property
            def contract(self):
                return _SwapContract()

            @property
            def participants(self):
                return [self.party_a.owning_key, self.party_b.owning_key]

            @property
            def parties(self):
                return [self.party_a, self.party_b]

        net = MockNetwork(verifier=CpuVerifier())
        try:
            notary = net.create_notary_node("Notary")
            alice = net.create_node("Alice")
            bob = net.create_node("Bob")

            accepted_terms = []

            from corda_tpu.flows.api import register_flow

            @register_flow(name="SwapAcceptor")
            class SwapAcceptor(DealAcceptorFlow):
                def validate_terms(self, deal):
                    accepted_terms.append(deal.notional)
                    if deal.notional > 1_000_000:
                        raise FlowException("notional too large")

            bob.register_initiated_flow(
                "DealInstigatorFlow", lambda party: SwapAcceptor(party))

            deal = SwapDeal(alice.identity, bob.identity, 500_000)
            handle = alice.start_flow(DealInstigatorFlow(
                bob.identity, deal, SwapCommand(), notary.identity))
            net.run_network()
            final = handle.result.result()
            assert accepted_terms == [500_000]
            assert len(final.sigs) == 3  # alice + bob + notary
            for node in (alice, bob):
                assert node.services.storage_service.validated_transactions \
                    .get_transaction(final.id) is not None
        finally:
            net.stop_nodes()
