"""Perf-doctor contract tests (round 17).

Three claims, matching the acceptance criteria:

  * backfill over the nine checked-in artifacts reproduces the two
    known diagnoses — the r05 flagship kernel-gap (sidecar-era
    occupancy bottleneck) and INGEST_r15's ``first_bottleneck =
    "rounds"`` server wall;
  * the verdict machinery is honest arithmetic — roofline gap factors,
    rule-table attribution on synthetic breakdowns, abstention below
    the min-rounds floor;
  * the gate exits nonzero on a synthetic >=20% regression and zero on
    the real trajectory.
"""

import json
import os

import pytest

from corda_tpu.obs import doctor
from corda_tpu.tools import perfdoctor

ARTIFACTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "artifacts")


# ---------------------------------------------------------------------------
# Backfill over the checked-in history
# ---------------------------------------------------------------------------


def test_backfill_covers_all_checked_in_artifacts(tmp_path, capsys):
    store = tmp_path / "TRAJECTORY.jsonl"
    code = perfdoctor.main(["--backfill", ARTIFACTS,
                            "--trajectory", str(store)])
    assert code == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["skipped"] == []
    records = doctor.load_trajectory(str(store))
    assert len(records) == 9
    sources = [r["source"] for r in records]
    # Deterministic chronological order: (round, filename).
    assert sources == sorted(
        sources, key=lambda s: (doctor._round_of({}, s), s))
    assert {r["kind"] for r in records} == {
        "autotune", "bench_report", "flagship_capture", "ingest_sweep",
        "multichip_capture"}
    # Idempotent: a re-run rebuilds the identical store.
    before = store.read_text()
    assert perfdoctor.main(["--backfill", ARTIFACTS,
                            "--trajectory", str(store)]) == 0
    assert store.read_text() == before


def test_backfill_reproduces_known_diagnoses(tmp_path):
    store = tmp_path / "TRAJECTORY.jsonl"
    assert perfdoctor.main(["--backfill", ARTIFACTS,
                            "--trajectory", str(store)]) == 0
    by_source = {r["source"]: r
                 for r in doctor.load_trajectory(str(store))}
    # The r05 flagship kernel-gap: every r05 report diagnoses the
    # sidecar-era occupancy bottleneck (micro-batches host-routed).
    for letter in "abcde":
        rec = by_source[f"BENCH_r05_local_{letter}.json"]
        assert rec["verdict"]["first_bottleneck"] == "device_occupancy"
    # The flagship report's gap factor is the measured ~100x kernel gap.
    assert by_source["BENCH_r05_local_e.json"]["verdict"][
        "gap_factor"] == pytest.approx(100.0, rel=0.01)
    # INGEST_r15: the server wall — unanimous busiest_stage across the
    # member stamps.
    assert by_source["INGEST_r15_local.json"]["verdict"][
        "first_bottleneck"] == "rounds"
    # The r06 sidecar flagship ran at occupancy 1.0: no occupancy
    # verdict, and nothing else implicated — an honest None.
    assert by_source["BENCH_r06_flagship_sidecar_local.json"][
        "verdict"]["first_bottleneck"] is None


def test_checked_in_trajectory_matches_backfill(tmp_path):
    """The committed artifacts/TRAJECTORY.jsonl IS the backfill output —
    regenerating it must be a no-op (anything else means the store in
    the tree is stale relative to the doctor's schema)."""
    committed = os.path.join(ARTIFACTS, "TRAJECTORY.jsonl")
    assert os.path.exists(committed), (
        "artifacts/TRAJECTORY.jsonl missing — run "
        "`python -m corda_tpu.tools.perfdoctor --backfill artifacts/`")
    store = tmp_path / "TRAJECTORY.jsonl"
    assert perfdoctor.main(["--backfill", ARTIFACTS,
                            "--trajectory", str(store)]) == 0
    assert store.read_text() == open(committed, encoding="utf-8").read()


# ---------------------------------------------------------------------------
# Roofline arithmetic
# ---------------------------------------------------------------------------


def test_roofline_gap_and_layer_attribution():
    signals = {"kind": "bench_report",
               "ceiling_sigs_per_sec": 100_000.0,
               "ceiling_source": "kernel_stream",
               "e2e_sigs_per_sec": 2_000.0,
               "committed_tx_per_sec": 40.0,
               "device_occupancy_by_member": {"Raft0": 0.5}}
    verdict = doctor.diagnose(signals)
    roof = verdict["roofline"]
    assert roof["gap_factor"] == 50.0
    # Occupancy 0.5 explains a 2x slice of the gap; the remaining 25x is
    # attributed to nothing — residual, not invented precision.
    assert roof["layers"]["verify_routing_factor"] == 2.0
    assert roof["layers"]["residual_factor"] == 25.0
    assert verdict["first_bottleneck"] == "device_occupancy"


def test_roofline_zero_occupancy_attributes_whole_gap():
    signals = {"ceiling_sigs_per_sec": 10_000.0,
               "e2e_sigs_per_sec": 1_000.0,
               "device_occupancy_by_member": {"N": 0.0}}
    roof = doctor.diagnose(signals)["roofline"]
    assert roof["gap_factor"] == 10.0
    assert roof["layers"]["verify_routing_factor"] == 10.0
    assert roof["layers"]["residual_factor"] == 1.0


def test_roofline_abstains_without_both_sides():
    roof = doctor.diagnose({"e2e_sigs_per_sec": 500.0})["roofline"]
    assert roof["gap_factor"] is None and roof["layers"] is None


# ---------------------------------------------------------------------------
# Rule-table attribution on synthetic signals
# ---------------------------------------------------------------------------


def _breakdown(shares, rounds=100):
    wall = 10.0
    return {"rounds": rounds, "wall_s": wall,
            "phases": {p: {"total_s": wall * s, "share": s}
                       for p, s in shares.items()}}


def test_dominant_seal_phase_maps_to_amortization_rule():
    stamps = {"Raft0": {"round_breakdown": _breakdown(
        {"seal": 0.6, "replicate": 0.2, "apply": 0.1})}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "seal"
    top = verdict["bottlenecks"][0]
    assert "amortization" in top["next_experiment"]
    assert top["evidence"]["round_breakdown_shares"]["seal"] == 0.6


def test_breakdown_below_min_rounds_abstains():
    stamps = {"Raft0": {"round_breakdown": _breakdown(
        {"seal": 0.9}, rounds=doctor.MIN_ATTRIBUTION_ROUNDS - 1)}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] is None
    assert verdict["bottlenecks"] == []


def test_low_occupancy_outranks_minor_phase():
    stamps = {"Raft0": {"device_batches": 1, "host_batches": 9,
                        "round_breakdown": _breakdown(
                            {"seal": 0.35, "poll": 0.3})}}
    verdict = doctor.stamp_attribution(stamps)
    # Occupancy 0.1 scores 0.9; seal at share 0.35 scores 0.675.
    assert verdict["first_bottleneck"] == "device_occupancy"
    causes = [b["cause"] for b in verdict["bottlenecks"]]
    assert causes == ["device_occupancy", "seal"]
    assert "coalesce" in verdict["bottlenecks"][0]["next_experiment"]


def test_shed_dominated_admission_maps_to_recalibration_rule():
    stamps = {"Notary": {"admission": {"admitted_interactive": 50,
                                       "admitted_bulk": 10,
                                       "shed_interactive": 0,
                                       "shed_bulk": 40}}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "admission"
    top = verdict["bottlenecks"][0]
    assert top["evidence"]["shed_fraction"] == 0.4
    assert "calibrate_admission" in top["next_experiment"]


def test_pad_fraction_rule_fires_from_artifact_signals():
    verdict = doctor.diagnose({"pad_fraction": 0.45,
                               "batch_sigs_hist": {"256": 10}})
    assert verdict["first_bottleneck"] == "pad_fraction"
    assert "bucket ladder" in verdict["bottlenecks"][0]["next_experiment"]


def test_unknown_stage_gets_generic_suggestion():
    stamps = {"A": {"busiest_stage": "wire_decode"}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "wire_decode"
    assert "wire_decode" in verdict["bottlenecks"][0]["next_experiment"]


def test_pipelined_rounds_verdict_suggests_executor_levers():
    """Round 18: a "rounds" verdict from members stamping pipeline=true
    must suggest the NEXT experiment (apply-queue depth / native
    commit_many sweep) — re-suggesting round-loop amortization the
    pipelined plane has already applied would send the operator in a
    circle."""
    stamps = {"Raft0": {"busiest_stage": "rounds",
                        "raft": {"pipeline": True, "role": "leader"}},
              "Raft1": {"busiest_stage": "rounds",
                        "raft": {"pipeline": True, "role": "follower"}}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "rounds"
    top = verdict["bottlenecks"][0]
    assert "apply_queue_depth" in top["next_experiment"]
    assert "commit_many" in top["next_experiment"]
    assert "amortize" not in top["next_experiment"]


def test_serial_rounds_verdict_keeps_round_loop_amortization_rule():
    stamps = {"Raft0": {"busiest_stage": "rounds",
                        "raft": {"pipeline": False}}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "rounds"
    top = verdict["bottlenecks"][0]
    # The serial loop still gets the amortization suggestion verbatim.
    assert top["next_experiment"] == doctor.RULES["rounds"]
    assert "apply_queue_depth" not in top["next_experiment"]


def test_pipelined_dominant_apply_phase_maps_to_executor_rule():
    stamps = {"Raft0": {"raft": {"pipeline": True},
                        "round_breakdown": _breakdown(
                            {"apply": 0.6, "seal": 0.1, "poll": 0.1})}}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "apply"
    top = verdict["bottlenecks"][0]
    assert "apply_queue_depth" in top["next_experiment"]
    assert "commit_many" in top["next_experiment"]
    # The same breakdown WITHOUT the pipeline stamp keeps the serial rule.
    serial = doctor.stamp_attribution(
        {"Raft0": {"round_breakdown": _breakdown(
            {"apply": 0.6, "seal": 0.1, "poll": 0.1})}})
    assert serial["bottlenecks"][0]["next_experiment"] \
        == doctor.RULES["apply"]


def _fed_stamp(shares, occs=None, dispatches_total=100):
    """A member stamp whose sidecar block carries a federation routing
    view (FederatedVerifier.federation_stats shape, trimmed)."""
    hosts = {}
    for i, (addr, share) in enumerate(sorted(shares.items())):
        hosts[addr] = {"dispatches": int(share * dispatches_total),
                       "server": ({"device_batches": None,
                                   "device_occupancy": (occs or {}).get(addr)}
                                  if occs else None)}
    return {"sidecar": {"federation": {
        "hosts": hosts, "hedges": 7, "host_degraded": 0}}}


def test_host_imbalance_rule_fires_on_routing_share_skew():
    stamps = {"Notary": _fed_stamp(
        {"h0.sock": 0.8, "h1.sock": 0.2},
        occs={"h0.sock": 0.9, "h1.sock": 0.2})}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "host_imbalance"
    top = verdict["bottlenecks"][0]
    # Skew 0.6 -> score 0.8; the experiment names the two levers.
    assert top["score"] == 0.8
    assert "rebalance" in top["next_experiment"]
    assert "hedge" in top["next_experiment"]
    # Evidence pairs each host's routed share with its own occupancy.
    assert top["evidence"]["routing_share_by_host"] == {
        "h0.sock": 0.8, "h1.sock": 0.2}
    assert top["evidence"]["occupancy_by_host"] == {
        "h0.sock": 0.9, "h1.sock": 0.2}
    assert top["evidence"]["hedges"] == 7


def test_host_imbalance_abstains_on_balanced_routing():
    stamps = {"Notary": _fed_stamp({"h0.sock": 0.55, "h1.sock": 0.45})}
    verdict = doctor.stamp_attribution(stamps)
    # Skew 0.1 < threshold: the router's depth balancing is working.
    assert all(b["cause"] != "host_imbalance"
               for b in verdict["bottlenecks"])
    # Single-host "federations" and sidecar-less members never fire it.
    assert doctor.stamp_attribution(
        {"A": _fed_stamp({"h0.sock": 1.0})})["first_bottleneck"] is None
    assert doctor.stamp_attribution(
        {"A": {"sidecar": None}})["first_bottleneck"] is None


def test_host_imbalance_merges_dispatches_across_members():
    # Two members each skewed toward a DIFFERENT host: the cluster-wide
    # routing is balanced, so the merged verdict must abstain — a
    # per-member diagnosis would fire twice and be wrong both times.
    stamps = {"A": _fed_stamp({"h0.sock": 0.8, "h1.sock": 0.2}),
              "B": _fed_stamp({"h0.sock": 0.2, "h1.sock": 0.8})}
    verdict = doctor.stamp_attribution(stamps)
    assert all(b["cause"] != "host_imbalance"
               for b in verdict["bottlenecks"])
    # Both skewed the SAME way sums to a cluster-wide imbalance.
    stamps = {"A": _fed_stamp({"h0.sock": 0.8, "h1.sock": 0.2}),
              "B": _fed_stamp({"h0.sock": 0.7, "h1.sock": 0.3})}
    verdict = doctor.stamp_attribution(stamps)
    assert verdict["first_bottleneck"] == "host_imbalance"
    assert verdict["bottlenecks"][0]["evidence"][
        "routing_share_by_host"] == {"h0.sock": 0.75, "h1.sock": 0.25}


def test_stamp_attribution_empty_and_scalar_polluted_stamps():
    assert doctor.stamp_attribution({})["first_bottleneck"] is None
    assert doctor.stamp_attribution(None)["first_bottleneck"] is None
    # Historical artifacts carry scalar siblings among the member dicts.
    verdict = doctor.stamp_attribution(
        {"device_warm_wait_s": 3.2,
         "Raft0": {"busiest_stage": "fsync"}})
    assert verdict["members"] == 1
    assert verdict["first_bottleneck"] == "fsync"


# ---------------------------------------------------------------------------
# Gate exit codes
# ---------------------------------------------------------------------------


def _rec(kind, source, **metrics):
    return {"schema": doctor.SCHEMA_VERSION, "kind": kind,
            "source": source, "round": None, "metrics": metrics,
            "verdict": {"first_bottleneck": None, "bottlenecks": [],
                        "gap_factor": None}}


def _write_store(path, records):
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_gate_trips_on_20pct_p99_regression(tmp_path, capsys):
    store = tmp_path / "t.jsonl"
    _write_store(store, [
        _rec("ingest_sweep", "old.json", p99_ms=100.0,
             peak_achieved_tx_s=200.0),
        _rec("ingest_sweep", "new.json", p99_ms=125.0,  # +25% > 20% band
             peak_achieved_tx_s=200.0)])
    code = perfdoctor.main(["--gate", "--trajectory", str(store)])
    assert code == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is False
    hit = verdict["regressions"][0]
    assert hit["metric"] == "p99_ms" and hit["change_pct"] == 25.0


def test_gate_trips_on_sigs_per_sec_drop(tmp_path):
    store = tmp_path / "t.jsonl"
    _write_store(store, [
        _rec("bench_report", "old.json", flagship_sigs_per_sec=1000.0),
        _rec("bench_report", "new.json", flagship_sigs_per_sec=750.0)])
    assert perfdoctor.main(["--gate", "--trajectory", str(store)]) == 1


def test_gate_passes_inside_band_and_compares_only_newest_pair(tmp_path,
                                                               capsys):
    store = tmp_path / "t.jsonl"
    _write_store(store, [
        # An ancient catastrophic record must NOT trip the gate — only
        # the newest pair of each kind is judged.
        _rec("bench_report", "ancient.json", flagship_sigs_per_sec=9e9),
        _rec("bench_report", "old.json", flagship_sigs_per_sec=1000.0,
             flagship_p99_ms=200.0),
        _rec("bench_report", "new.json", flagship_sigs_per_sec=850.0,
             flagship_p99_ms=230.0)])  # -15% and +15%: inside the band
    assert perfdoctor.main(["--gate", "--trajectory", str(store)]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is True
    assert verdict["compared"]["bench_report"] == {
        "prev": "old.json", "new": "new.json"}


def test_gate_never_compares_across_kinds(tmp_path):
    store = tmp_path / "t.jsonl"
    _write_store(store, [
        _rec("bench_report", "bench.json", p99_ms=10.0),
        _rec("ingest_sweep", "ingest.json", p99_ms=6000.0)])
    assert perfdoctor.main(["--gate", "--trajectory", str(store)]) == 0


def test_gate_equal_metric_trips_on_flag_flip(tmp_path):
    store = tmp_path / "t.jsonl"
    _write_store(store, [
        _rec("ingest_sweep", "old.json", exactly_once_all=True),
        _rec("ingest_sweep", "new.json", exactly_once_all=False)])
    assert perfdoctor.main(["--gate", "--trajectory", str(store)]) == 1


def test_gate_policy_override(tmp_path):
    store = tmp_path / "t.jsonl"
    _write_store(store, [
        _rec("ingest_sweep", "old.json", p99_ms=100.0),
        _rec("ingest_sweep", "new.json", p99_ms=125.0)])
    policy = tmp_path / "policy.json"
    policy.write_text(json.dumps(
        {"p99_ms": {"direction": "lower", "pct": 50.0}}))
    assert perfdoctor.main(["--gate", "--trajectory", str(store),
                            "--policy", str(policy)]) == 0


def test_gate_exits_zero_on_real_trajectory(tmp_path):
    """The acceptance criterion: the checked-in history passes the gate
    (rebuilt fresh so this cannot silently test a stale store)."""
    store = tmp_path / "TRAJECTORY.jsonl"
    assert perfdoctor.main(["--backfill", ARTIFACTS,
                            "--trajectory", str(store)]) == 0
    assert perfdoctor.main(["--gate", "--trajectory", str(store)]) == 0


def test_gate_errors_cleanly_without_store(tmp_path, capsys):
    code = perfdoctor.main(["--gate", "--trajectory",
                            str(tmp_path / "absent.jsonl")])
    assert code == 2
    assert "backfill" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Diagnose CLI + store plumbing
# ---------------------------------------------------------------------------


def test_diagnose_cli_one_verdict_line_per_artifact(capsys):
    code = perfdoctor.main([
        os.path.join(ARTIFACTS, "BENCH_r05_local_e.json"),
        os.path.join(ARTIFACTS, "INGEST_r15_local.json")])
    assert code == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    first, second = (json.loads(line) for line in lines)
    assert first["first_bottleneck"] == "device_occupancy"
    assert first["roofline"]["gap_factor"] == pytest.approx(100.0,
                                                            rel=0.01)
    assert second["first_bottleneck"] == "rounds"


def test_load_trajectory_rejects_corruption(tmp_path):
    store = tmp_path / "t.jsonl"
    store.write_text('{"kind": "bench_report"}\nnot json\n')
    with pytest.raises(ValueError, match="malformed"):
        doctor.load_trajectory(str(store))


def test_append_then_load_round_trips(tmp_path):
    store = tmp_path / "nested" / "t.jsonl"
    rec = _rec("bench_report", "x.json", value_sigs_per_sec=1.0)
    doctor.append_trajectory(str(store), rec)
    doctor.append_trajectory(str(store), rec)
    assert doctor.load_trajectory(str(store)) == [rec, rec]


# ---------------------------------------------------------------------------
# Partition plane (round 20): election churn rule + partition_chaos gate
# ---------------------------------------------------------------------------


def _raft_stamp(**kw):
    base = {"term": 2, "elections_won": 1, "leader_stepdowns": 0,
            "checkquorum_stepdowns": 0, "prevote_rejections": 0,
            "commit_index": 100, "prevote": False}
    base.update(kw)
    return base


def test_election_churn_rule_fires_on_disturbed_leadership():
    stamps = {f"m{i}": {"raft": _raft_stamp(elections_won=2,
                                            leader_stepdowns=1,
                                            term=9)}
              for i in range(3)}
    verdict = doctor.stamp_attribution(stamps)
    churn = next(b for b in verdict["bottlenecks"]
                 if b["cause"] == "election_churn")
    assert churn["evidence"]["elections_won"] == 6
    assert churn["evidence"]["max_term"] == 9
    assert "prevote" in churn["next_experiment"]


def test_election_churn_abstains_on_healthy_or_idle_clusters():
    # One clean election per group (the winner stamps it; a 4-shard run
    # sums to 4): not churn.
    healthy = {f"m{i}": {"raft": _raft_stamp(
        elections_won=1 if i % 3 == 0 else 0)} for i in range(12)}
    assert not any(b["cause"] == "election_churn" for b in
                   doctor.stamp_attribution(healthy)["bottlenecks"])
    # Plenty of elections but almost no committed work: a near-idle
    # bootstrap, below the MIN_ATTRIBUTION_ROUNDS abstention floor.
    idle = {f"m{i}": {"raft": _raft_stamp(elections_won=5,
                                          commit_index=3)}
            for i in range(3)}
    assert not any(b["cause"] == "election_churn" for b in
                   doctor.stamp_attribution(idle)["bottlenecks"])


def test_partition_chaos_metrics_hoist_and_gate_on_linearizability():
    art = {"metric": "verified_sigs_per_sec", "value": 100.0,
           "partition_chaos": {"recovery_s": 0.2, "max_term_inflation": 1,
                               "minority_commits": 0, "lost_acks": 0,
                               "history_linearizable": True}}
    rec1 = doctor.normalize_record(art, "r20_a.json")
    m = rec1["metrics"]
    assert m["recovery_s"] == 0.2
    assert m["max_term_inflation"] == 1.0
    assert m["history_linearizable"] is True

    art2 = dict(art)
    art2["partition_chaos"] = dict(
        art["partition_chaos"], history_linearizable=False,
        max_term_inflation=9)
    rec2 = doctor.normalize_record(art2, "r20_b.json")
    verdict = doctor.gate([rec1, rec2])
    assert not verdict["ok"]
    tripped = {r["metric"] for r in verdict["regressions"]}
    assert "history_linearizable" in tripped  # the hard flag
    assert "max_term_inflation" in tripped    # the banded A/B bound
