"""The QoS plane (corda_tpu/qos/): priority lanes, admission control and
deadline-aware coalescing.

Covers the ISSUE acceptance list for the round-12 subsystem:

* QosContext wire codec (17-byte <BQQ field; junk decodes to None, never
  an exception) and the plane's arming/env-parsing/link-map-bound
  behaviour, mirroring the obs/trace discipline;
* AdmissionController token buckets + queue-depth watermark (bulk sheds,
  interactive and unlabelled admit; retry-after is bounded);
* SMM lane scheduling: interactive-first with the bulk_every
  anti-starvation ratio, and the DISARMED path staying strict pop(0)
  FIFO — the bit-identical guarantee;
* deadline-aware early flush at all three queueing points: the SMM
  verify micro-batch (verify_deadline_pressure + the sidecar hint), the
  sidecar server's deadline scheduler (OP_VERIFY_QOS over a real unix
  socket), and the Raft leader's group-commit seal;
* overload shed + retry: a bulk client is shed with a retryable
  OverloadedError, notarise_with_retry backs off, and the retry commits
  EXACTLY once (first-committer-wins log shows one consuming tx).
"""

import os
import sys
import time
import types

import pytest

from corda_tpu.crypto import sidecar as sc
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.provider import CpuVerifier, VerifyJob
from corda_tpu.flows.api import FlowLogic
from corda_tpu.flows.notary import (
    NotaryException,
    OverloadedError,
    notarise_with_retry,
)
from corda_tpu.node.messaging.tcp import TcpMessaging
from corda_tpu.node.statemachine import StateMachineManager
from corda_tpu.qos import context as qos
from corda_tpu.qos.admission import MAX_RETRY_AFTER_S, AdmissionController
from corda_tpu.testing import DummyContract
from corda_tpu.testing.mock_network import MockNetwork

sys.path.insert(0, os.path.dirname(__file__))
from test_raft_group_commit import (  # noqa: E402
    Net,
    cmd,
    elect,
    make_trio,
    settle,
)


@pytest.fixture()
def plane():
    p = qos.arm("test")
    yield p
    qos.disarm()


def _fsm(ctx):
    """Minimal FlowStateMachine stand-in for the scheduler unit tests."""
    return types.SimpleNamespace(qos=ctx, qos_runnable_since=None,
                                 trace_id=None, trace_span=None)


# ---------------------------------------------------------------------------
# QosContext codec + plane arming
# ---------------------------------------------------------------------------


def test_context_wire_roundtrip():
    ctx = qos.QosContext(qos.LANE_BULK, deadline_ns=123456789,
                         admitted_ns=987654321)
    raw = ctx.to_wire()
    assert len(raw) == qos.WIRE_SIZE == 17
    assert qos.QosContext.from_wire(raw) == ctx


def test_context_from_wire_rejects_junk_without_raising():
    good = qos.QosContext().to_wire()
    assert qos.QosContext.from_wire(good) is not None
    assert qos.QosContext.from_wire(good[:-1]) is None       # short
    assert qos.QosContext.from_wire(good + b"x") is None     # long
    assert qos.QosContext.from_wire("not-bytes") is None     # wrong type
    assert qos.QosContext.from_wire(b"\xff" + good[1:]) is None  # bad lane


def test_new_context_derives_deadline_for_interactive_only(plane):
    t0 = qos.now_ns()
    ictx = plane.new_context(qos.LANE_INTERACTIVE, slo_ms=100.0)
    assert ictx.deadline_ns >= t0 + int(99 * 1e6)
    assert ictx.admitted_ns >= t0
    bctx = plane.new_context(qos.LANE_BULK, slo_ms=100.0)
    assert bctx.deadline_ns == 0  # bulk is the sheddable, deadline-free class


def test_near_deadline_is_interactive_only_and_guarded(plane):
    soon = qos.QosContext(qos.LANE_INTERACTIVE,
                          deadline_ns=qos.now_ns() + 1_000_000)
    far = qos.QosContext(qos.LANE_INTERACTIVE,
                         deadline_ns=qos.now_ns() + 10 ** 12)
    bulk = qos.QosContext(qos.LANE_BULK, deadline_ns=qos.now_ns())
    assert plane.near_deadline(soon)        # inside the 5 ms default guard
    assert not plane.near_deadline(far)
    assert not plane.near_deadline(bulk)    # bulk never triggers a flush
    assert not plane.near_deadline(None)
    assert not plane.near_deadline(qos.QosContext())  # no deadline stamped


def test_arm_from_env(monkeypatch):
    try:
        monkeypatch.delenv(qos.ENV_VAR, raising=False)
        assert qos.arm_from_env("n") is None
        monkeypatch.setenv(qos.ENV_VAR, "off")
        assert qos.arm_from_env("n") is None
        monkeypatch.setenv(qos.ENV_VAR, "on")
        p = qos.arm_from_env("n")
        assert p is not None and p.slo_ms == 50.0 and p.bulk_every == 4
        monkeypatch.setenv(qos.ENV_VAR, "slo_ms=75,guard_ms=2,bulk_every=3")
        p = qos.arm_from_env("n")
        assert p.slo_ms == 75.0
        assert p.deadline_guard_ns == 2_000_000
        assert p.bulk_every == 3
    finally:
        qos.disarm()


def test_link_map_is_bounded(plane):
    for i in range(qos.LINK_MAP_MAX + 5):
        plane.register_link(i.to_bytes(8, "big"), qos.QosContext())
    # Wholesale clear at the cap: correlation loss beats unbounded growth.
    assert len(plane._links) <= qos.LINK_MAP_MAX
    assert plane.counters["links_dropped"] >= qos.LINK_MAP_MAX


# ---------------------------------------------------------------------------
# Admission controller
# ---------------------------------------------------------------------------


def test_admission_unlimited_rate_admits_everything():
    adm = AdmissionController()
    for _ in range(100):
        assert adm.admit(qos.LANE_BULK) is None
        assert adm.admit(qos.LANE_INTERACTIVE) is None
    stats = adm.stats()
    assert stats["shed_bulk"] == 0 and stats["shed_interactive"] == 0


def test_admission_bulk_bucket_sheds_with_bounded_retry_after():
    adm = AdmissionController(bulk_rate=0.5, bulk_burst=2.0)
    assert adm.admit(qos.LANE_BULK) is None
    assert adm.admit(qos.LANE_BULK) is None
    retry = adm.admit(qos.LANE_BULK)  # burst spent; refill is 2 s/token
    assert retry is not None and 0.0 < retry <= MAX_RETRY_AFTER_S
    # The interactive bucket is independent: still unlimited here.
    assert adm.admit(qos.LANE_INTERACTIVE) is None
    stats = adm.stats()
    assert stats["admitted_bulk"] == 2 and stats["shed_bulk"] == 1


def test_admission_watermark_sheds_bulk_only():
    adm = AdmissionController(queue_watermark=5)
    assert adm.admit(qos.LANE_BULK, queue_depth=5) is None   # at, not over
    retry = adm.admit(qos.LANE_BULK, queue_depth=6)
    assert retry is not None and 0.0 < retry <= MAX_RETRY_AFTER_S
    # Interactive rides over the watermark: depth pressure sheds only the
    # deprioritised class.
    assert adm.admit(qos.LANE_INTERACTIVE, queue_depth=1000) is None
    assert adm.stats()["watermark_sheds"] == 1


def test_admission_unknown_lane_uses_interactive_bucket():
    adm = AdmissionController(interactive_rate=0.5, interactive_burst=1.0)
    assert adm.admit("mystery") is None
    assert adm.admit("mystery") is not None  # drained the interactive burst
    assert adm.stats()["shed_interactive"] == 1


# ---------------------------------------------------------------------------
# SMM lane scheduling (queueing point 1: the flow run queue)
# ---------------------------------------------------------------------------


def _drain(mgr):
    order = []
    while mgr._runnable:
        order.append(StateMachineManager._next_runnable(mgr))
    return order


def test_disarmed_scheduler_is_strict_fifo():
    assert qos.ACTIVE is None
    fsms = [_fsm(None) for _ in range(5)]
    mgr = types.SimpleNamespace(_runnable=list(fsms), _qos_pick_counter=0)
    assert _drain(mgr) == fsms          # pop(0), the pre-QoS behaviour
    assert mgr._qos_pick_counter == 0   # the counter never even moves


def test_armed_scheduler_serves_interactive_first_with_antistarvation(plane):
    i = [_fsm(plane.new_context(qos.LANE_INTERACTIVE)) for _ in range(4)]
    b = [_fsm(plane.new_context(qos.LANE_BULK)) for _ in range(3)]
    mgr = types.SimpleNamespace(
        _runnable=[b[0], i[0], b[1], i[1], i[2], i[3], b[2]],
        _qos_pick_counter=0)
    # Every 4th pick (bulk_every=4) takes the oldest bulk step while both
    # classes are runnable; once one class drains, FIFO within the other.
    assert _drain(mgr) == [i[0], i[1], i[2], b[0], i[3], b[1], b[2]]
    assert plane.counters["bulk_antistarvation_picks"] == 1


def test_antistarvation_ratio_holds_under_sustained_mixed_load(plane):
    inter = [_fsm(plane.new_context(qos.LANE_INTERACTIVE))
             for _ in range(40)]
    bulk = [_fsm(plane.new_context(qos.LANE_BULK)) for _ in range(40)]
    mixed = [f for pair in zip(bulk, inter) for f in pair]
    mgr = types.SimpleNamespace(_runnable=mixed, _qos_pick_counter=0)
    order = _drain(mgr)
    # While both classes are runnable the pattern is i,i,i,b repeating:
    # 52 picks drain 39 interactive + 13 bulk, bulk exactly at every
    # 4th slot — the 1-in-bulk_every anti-starvation contract.
    head = order[:52]
    bulk_positions = [k for k, f in enumerate(head)
                      if f.qos.lane == qos.LANE_BULK]
    assert bulk_positions == [3, 7, 11, 15, 19, 23, 27, 31, 35, 39, 43,
                              47, 51]
    assert plane.counters["bulk_antistarvation_picks"] == 13
    # Unlabelled flows schedule WITH interactive (never starved by bulk).
    mgr2 = types.SimpleNamespace(
        _runnable=[_fsm(plane.new_context(qos.LANE_BULK)), _fsm(None)],
        _qos_pick_counter=0)
    assert StateMachineManager._next_runnable(mgr2).qos is None


def test_unlabelled_only_queue_keeps_exact_fifo_when_armed(plane):
    fsms = [_fsm(None) for _ in range(6)]
    mgr = types.SimpleNamespace(_runnable=list(fsms), _qos_pick_counter=0)
    assert _drain(mgr) == fsms
    assert plane.counters["bulk_antistarvation_picks"] == 0


# ---------------------------------------------------------------------------
# Deadline pressure on the SMM verify micro-batch + the sidecar hint
# ---------------------------------------------------------------------------


def _verify_mgr():
    return types.SimpleNamespace(
        _verify_queue=[], _verify_waiting_since=0.0, _verify_sig_count=0,
        _verify_qos_deadline_ns=0, verifier=types.SimpleNamespace())


def _req(n_sigs=1):
    return types.SimpleNamespace(
        stx=types.SimpleNamespace(sigs=[object()] * n_sigs))


def test_enqueue_verify_tracks_min_interactive_deadline(plane):
    mgr = _verify_mgr()
    now = qos.now_ns()
    StateMachineManager._enqueue_verify(
        mgr, _fsm(qos.QosContext(qos.LANE_INTERACTIVE, now + 500)), _req())
    StateMachineManager._enqueue_verify(
        mgr, _fsm(qos.QosContext(qos.LANE_INTERACTIVE, now + 300)), _req())
    StateMachineManager._enqueue_verify(
        mgr, _fsm(qos.QosContext(qos.LANE_BULK, now + 1)), _req())
    StateMachineManager._enqueue_verify(mgr, _fsm(None), _req())
    assert mgr._verify_qos_deadline_ns == now + 300  # bulk never lowers it
    assert len(mgr._verify_queue) == 4


def test_verify_deadline_pressure_flags_only_near_deadlines(plane):
    mgr = _verify_mgr()
    mgr._verify_queue = [object()]
    mgr._verify_qos_deadline_ns = qos.now_ns() + 1_000_000  # inside guard
    assert StateMachineManager.verify_deadline_pressure(mgr)
    mgr._verify_qos_deadline_ns = qos.now_ns() + 10 ** 12
    assert not StateMachineManager.verify_deadline_pressure(mgr)
    mgr._verify_qos_deadline_ns = 0
    assert not StateMachineManager.verify_deadline_pressure(mgr)
    mgr._verify_queue = []  # empty batch: nothing to flush early
    mgr._verify_qos_deadline_ns = qos.now_ns()
    assert not StateMachineManager.verify_deadline_pressure(mgr)


def test_verify_deadline_pressure_false_when_disarmed():
    assert qos.ACTIVE is None
    mgr = _verify_mgr()
    mgr._verify_queue = [object()]
    mgr._verify_qos_deadline_ns = 1
    assert not StateMachineManager.verify_deadline_pressure(mgr)


def test_qos_verify_hint_forwards_min_deadline_to_verifier(plane):
    mgr = _verify_mgr()
    mgr._verify_qos_deadline_ns = 123
    StateMachineManager._qos_verify_hint(mgr)
    assert mgr.verifier.qos_hint == (qos.LANE_INTERACTIVE, 123)
    mgr._verify_qos_deadline_ns = 0
    StateMachineManager._qos_verify_hint(mgr)
    assert mgr.verifier.qos_hint is None


def test_qos_queue_depth_counts_runnable_and_parked():
    mgr = types.SimpleNamespace(_runnable=[1, 2], _service_queue=[3])
    assert StateMachineManager.qos_queue_depth(mgr) == 3


# ---------------------------------------------------------------------------
# TCP wire frame: one extra field, only when armed + labelled
# ---------------------------------------------------------------------------


def test_wire_tuple_grows_one_field_only_when_armed():
    from corda_tpu.node.messaging.api import TopicSession

    fake = types.SimpleNamespace(
        my_address=types.SimpleNamespace(host="h", port=1))
    ts = TopicSession("t", 0)
    assert qos.ACTIVE is None
    base = TcpMessaging._wire_tuple(fake, ts, b"u", b"d")
    assert len(base) == 7  # the disarmed frame never grows
    try:
        plane = qos.arm("wire")
        assert len(TcpMessaging._wire_tuple(fake, ts, b"u", b"d")) == 7
        qos.set_context(plane.new_context(qos.LANE_BULK))
        armed = TcpMessaging._wire_tuple(fake, ts, b"u", b"d")
        assert len(armed) == 8
        decoded = qos.QosContext.from_wire(armed[7])
        assert decoded is not None and decoded.lane == qos.LANE_BULK
    finally:
        qos.disarm()


# ---------------------------------------------------------------------------
# Sidecar deadline scheduler (queueing point 2: cross-process batches)
# ---------------------------------------------------------------------------


def _sock_dir():
    import shutil
    import tempfile

    # Short /tmp path on purpose: AF_UNIX paths cap at ~108 bytes.
    d = tempfile.mkdtemp(prefix="qos-", dir="/tmp")
    return d, shutil.rmtree


def _good_job():
    kp = KeyPair.generate(b"\x09" * 32)
    msg = b"qos-deadline-flush".ljust(32, b".")
    sig = kp.sign(msg)
    return VerifyJob(bytes(sig.by.encoded), msg, bytes(sig.bytes))


def _verify_qos_rtt(sock, req_id, lane, deadline_ns):
    sc.send_frame(sock, sc.encode_verify_request_qos(
        req_id, [_good_job()], lane, deadline_ns))
    t0 = time.perf_counter()
    payload = sc.recv_frame(sock)
    elapsed = time.perf_counter() - t0
    op, rid, status, _tier, _wait, _verify = \
        sc._VERIFY_REPLY_HDR.unpack_from(payload)
    body = payload[sc._VERIFY_REPLY_HDR.size:]
    assert (op, rid, status) == (sc.OP_VERIFY, req_id, sc.STATUS_OK)
    assert body == b"\x01"  # the valid signature verified
    return elapsed


def test_sidecar_deadline_flushes_before_coalesce_window_closes():
    d, cleanup = _sock_dir()
    srv = sc.SidecarServer(os.path.join(d, "s.sock"),
                           verifier=CpuVerifier(), coalesce_us=600_000,
                           qos_guard_us=2_000).start()
    try:
        sock = sc.connect(srv.address, timeout=10.0)
        # A bulk request (no deadline) waits out the full 600 ms window.
        slow = _verify_qos_rtt(sock, 1, sc.LANE_CODE_BULK, 0)
        assert slow >= 0.45
        assert srv.qos_early_flushes == 0
        # An interactive deadline 50 ms out cuts the batch ~48 ms in:
        # deadline-aware coalescing across the process boundary.
        fast = _verify_qos_rtt(sock, 2, sc.LANE_CODE_INTERACTIVE,
                               time.time_ns() + 50_000_000)
        assert fast < 0.35
        assert srv.qos_early_flushes >= 1
        stats = srv.stats()
        assert stats["qos_bulk_requests"] == 1
        assert stats["qos_interactive_requests"] == 1
        sock.close()
    finally:
        srv.stop()
        cleanup(d, ignore_errors=True)


def test_sidecar_form_batch_packs_interactive_first():
    srv = sc.SidecarServer("/tmp/qos-unstarted.sock",
                           verifier=CpuVerifier(), max_sigs=2)
    jobs = lambda: [_good_job()]  # noqa: E731
    b1 = sc._Pending(None, 1, jobs(), lane=sc.LANE_CODE_BULK)
    i1 = sc._Pending(None, 2, jobs(), lane=sc.LANE_CODE_INTERACTIVE)
    b2 = sc._Pending(None, 3, jobs(), lane=sc.LANE_CODE_BULK)
    i2 = sc._Pending(None, 4, jobs(), lane=sc.LANE_CODE_INTERACTIVE)
    srv._pending.extend([b1, i1, b2, i2])
    batch, reordered = srv._form_batch()
    # max_sigs=2: the batch is cut from the latency-sensitive end (FIFO
    # within the class) and the deferred bulk keeps its arrival order.
    assert batch == [i1, i2] and reordered
    assert list(srv._pending) == [b1, b2]
    batch, reordered = srv._form_batch()
    assert batch == [b1, b2] and not reordered


def test_sidecar_form_batch_without_bulk_is_plain_fifo():
    srv = sc.SidecarServer("/tmp/qos-unstarted2.sock",
                           verifier=CpuVerifier(), max_sigs=4096)
    plain = sc._Pending(None, 1, [_good_job()])  # pre-QoS OP_VERIFY
    inter = sc._Pending(None, 2, [_good_job()],
                        lane=sc.LANE_CODE_INTERACTIVE)
    srv._pending.extend([plain, inter])
    batch, reordered = srv._form_batch()
    assert batch == [plain, inter] and not reordered  # bit-identical order


# ---------------------------------------------------------------------------
# Raft group-commit early seal (queueing point 3: the leader's batch)
# ---------------------------------------------------------------------------


def test_raft_leader_seals_batch_early_for_near_deadline(tmp_path, plane):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)

    far = cmd(b"r1", b"t1", b"rid-far")
    plane.register_link(far.request_id, qos.QosContext(
        qos.LANE_INTERACTIVE, deadline_ns=qos.now_ns() + 10 ** 12))
    leader.submit(far)
    # A comfortable deadline keeps the round coalescing as usual.
    assert leader.metrics["qos_early_seals"] == 0
    assert len(leader._pending_batch) == 1

    near = cmd(b"r2", b"t2", b"rid-near")
    plane.register_link(near.request_id, qos.QosContext(
        qos.LANE_INTERACTIVE, deadline_ns=qos.now_ns() + 1_000_000))
    leader.submit(near)
    # Inside the guard window: the buffer seals NOW instead of waiting
    # for the scheduling round to close.
    assert leader.metrics["qos_early_seals"] == 1
    assert not leader._pending_batch

    settle(net, list(members.values()))
    assert leader.decided[far.request_id].ok
    assert leader.decided[near.request_id].ok  # early seal still commits


def test_raft_bulk_and_unlinked_commands_never_force_a_seal(tmp_path, plane):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)

    bulk = cmd(b"r3", b"t3", b"rid-bulk")
    plane.register_link(bulk.request_id, qos.QosContext(
        qos.LANE_BULK, deadline_ns=qos.now_ns()))
    leader.submit(bulk)
    leader.submit(cmd(b"r4", b"t4", b"rid-unlinked"))
    assert leader.metrics["qos_early_seals"] == 0
    assert len(leader._pending_batch) == 2  # both ride the normal round

    settle(net, list(members.values()))
    assert leader.metrics["group_commits"] == 1


# ---------------------------------------------------------------------------
# Overload shed + retry (admission at the notarise entry point)
# ---------------------------------------------------------------------------


class _RetryingClient(FlowLogic):
    """notarise_with_retry wrapper: the production shed-recovery path."""

    def __init__(self, stx):
        self.stx = stx

    def call(self):
        sig = yield from notarise_with_retry(self, self.stx, retries=4)
        return sig


def _move_stx(net, notary, alice, bob):
    builder = DummyContract.generate_initial(
        alice.identity.ref(b"\x00"), 7, notary.identity)
    builder.sign_with(alice.key)
    issue_stx = builder.to_signed_transaction()
    alice.record_transaction(issue_stx)
    move = DummyContract.move(issue_stx.tx.out_ref(0),
                              bob.identity.owning_key)
    move.sign_with(alice.key)
    return move.to_signed_transaction(check_sufficient_signatures=False)


def test_bulk_shed_then_retry_commits_exactly_once(plane):
    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        alice = net.create_node("Alice")
        bob = net.create_node("Bob")
        admission = AdmissionController(bulk_rate=2.0, bulk_burst=1.0)
        notary.notary_service.admission = admission
        stx = _move_stx(net, notary, alice, bob)

        # Drain the single bulk token so the flow's first attempt is shed
        # (the overload chaos), then let the bucket refill (~0.5 s) while
        # notarise_with_retry parks on the server's retry-after floor.
        assert admission.admit(qos.LANE_BULK) is None
        handle = alice.smm.add(_RetryingClient(stx),
                               qos=plane.new_context(qos.LANE_BULK))
        net.run_network()

        assert handle.result.done and handle.result.exception() is None
        stats = admission.stats()
        # The bulk lane label PROPAGATED: the notary judged this flow in
        # the bulk bucket (shed), not the unlabelled/interactive default.
        assert stats["shed_bulk"] >= 1
        assert stats["admitted_bulk"] == 2  # the pre-drain + the retry
        # Exactly once: first-committer-wins log holds ONE consuming tx
        # for the input, and it is this tx — the shed attempt committed
        # nothing and the retry did not double-commit.
        committed = notary.uniqueness_provider._committed
        consumed = stx.tx.inputs[0]
        assert committed[consumed].id == stx.id
        assert sum(1 for c in committed.values() if c.id == stx.id) == 1
    finally:
        net.stop_nodes()


def test_shed_reply_carries_retryable_overload_error(plane):
    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        alice = net.create_node("Alice")
        bob = net.create_node("Bob")
        # Zero-burst-equivalent: one token, drained; no refill to speak of
        # (0.01/s) so EVERY bulk attempt inside the test window is shed.
        admission = AdmissionController(bulk_rate=0.01, bulk_burst=1.0)
        notary.notary_service.admission = admission
        assert admission.admit(qos.LANE_BULK) is None
        stx = _move_stx(net, notary, alice, bob)

        from corda_tpu.flows.notary import NotaryClientFlow

        # A RAW client (no retry wrapper) surfaces the shed to its caller.
        handle = alice.smm.add(NotaryClientFlow(stx),
                               qos=plane.new_context(qos.LANE_BULK))
        net.run_network()
        exc = handle.result.exception()
        assert isinstance(exc, NotaryException)
        assert isinstance(exc.error, OverloadedError)
        assert exc.error.lane == qos.LANE_BULK
        assert 0.0 < exc.error.retry_after_ms <= MAX_RETRY_AFTER_S * 1e3
        # Nothing was decided about the tx: the input is unconsumed.
        assert stx.tx.inputs[0] not in notary.uniqueness_provider._committed
    finally:
        net.stop_nodes()


# ---------------------------------------------------------------------------
# Stage registry (satellite: obs integration)
# ---------------------------------------------------------------------------


def test_qos_stages_registered_in_obs():
    from corda_tpu.obs import stages

    assert "admission_wait" in stages.DIRECT_STAGES
    assert "lane_queue_wait" in stages.DIRECT_STAGES
    assert "qos_flush" in stages.MARKER_SPANS
