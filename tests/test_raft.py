"""Raft notary cluster: replication, conflict detection, leader kill.

Mirrors the reference's DistributedNotaryTests (reference: node/src/
integration-test/kotlin/net/corda/node/services/DistributedNotaryTests.kt:
42-50 — real 3-member Raft cluster, commit + double-spend conflict) plus a
leader-kill/regroup case, over real TCP sockets and sqlite logs.
"""

import time

import pytest

from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.flows.notary import NotaryClientFlow, NotaryException
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node

import sys
import os
sys.path.insert(0, os.path.dirname(__file__))
from test_tcp_node import issue_and_move, pump_until  # noqa: E402


CLUSTER = ("RaftA", "RaftB", "RaftC")


def make_cluster(tmp_path):
    nodes = []
    for name in CLUSTER:
        nodes.append(Node(NodeConfig(
            name=name,
            base_dir=tmp_path / name,
            notary="raft-simple",
            raft_cluster=CLUSTER,
            network_map=tmp_path / "netmap.json",
        )).start())
    for n in nodes:
        n.refresh_netmap()
    return nodes


def wait_for_leader(members, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for node in members:
            node.run_once(timeout=0.005)
        leaders = [n for n in members if n.raft_member.role == "leader"]
        if leaders:
            return leaders[0]
    raise AssertionError("no leader elected")


def test_cluster_elects_leader_and_commits(tmp_path):
    nodes = make_cluster(tmp_path)
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "netmap.json")).start()
    everyone = nodes + [alice]
    try:
        leader = wait_for_leader(nodes)
        for n in everyone:
            n.refresh_netmap()

        # Notarise against the LEADER member (client picks one member).
        stx = issue_and_move(alice, leader.identity, magic=1)
        h = alice.start_flow(NotaryClientFlow(stx))
        pump_until(everyone, lambda: h.result.done)
        sig = h.result.result()
        sig.verify(stx.id.bytes)
        # The commit is REPLICATED: every member's state machine applied it.
        pump_until(everyone,
                   lambda: all(n.uniqueness_provider.committed_count == 1
                               for n in nodes))
    finally:
        for n in everyone:
            n.stop()


def test_double_spend_conflict_detected_by_cluster(tmp_path):
    nodes = make_cluster(tmp_path)
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "netmap.json")).start()
    everyone = nodes + [alice]
    try:
        leader = wait_for_leader(nodes)
        for n in everyone:
            n.refresh_netmap()

        from corda_tpu.testing.dummies import DummyContract

        builder = DummyContract.generate_initial(
            alice.identity.ref(b"\x01"), 2, leader.identity)
        builder.sign_with(alice.key)
        issue_stx = builder.to_signed_transaction()
        alice.services.record_transactions([issue_stx])
        prior = issue_stx.tx.out_ref(0)

        m1 = DummyContract.move(prior, alice.identity.owning_key)
        m1.sign_with(alice.key)
        stx1 = m1.to_signed_transaction(check_sufficient_signatures=False)
        m2 = DummyContract.move(prior, leader.identity.owning_key)
        m2.sign_with(alice.key)
        stx2 = m2.to_signed_transaction(check_sufficient_signatures=False)

        h1 = alice.start_flow(NotaryClientFlow(stx1))
        pump_until(everyone, lambda: h1.result.done)
        h1.result.result()

        h2 = alice.start_flow(NotaryClientFlow(stx2))
        pump_until(everyone, lambda: h2.result.done)
        with pytest.raises(NotaryException):
            h2.result.result()
    finally:
        for n in everyone:
            n.stop()


def test_leader_kill_cluster_regroups_and_commits(tmp_path):
    """Kill the elected leader; the survivors elect a new one and keep
    committing — with the dead member's committed state intact when it is
    reborn from disk."""
    nodes = make_cluster(tmp_path)
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "netmap.json")).start()
    survivors = [alice]
    try:
        leader = wait_for_leader(nodes)
        for n in nodes + [alice]:
            n.refresh_netmap()

        followers = [n for n in nodes if n is not leader]
        target = followers[0]  # notarise against a member that will survive

        stx = issue_and_move(alice, target.identity, magic=3)
        h = alice.start_flow(NotaryClientFlow(stx))
        pump_until(nodes + [alice], lambda: h.result.done)
        h.result.result()

        # -- kill the leader ------------------------------------------------
        leader.stop()
        dead_name = leader.config.name
        nodes.remove(leader)
        del leader
        survivors.extend(nodes)

        new_leader = wait_for_leader(nodes)
        assert new_leader.config.name != dead_name

        # A second notarisation still commits (quorum of 2 of 3).
        stx2 = issue_and_move(alice, target.identity, magic=4)
        h2 = alice.start_flow(NotaryClientFlow(stx2))
        pump_until(nodes + [alice], lambda: h2.result.done, timeout=20.0)
        h2.result.result()
        # Follower application trails the leader by a heartbeat; settle.
        pump_until(nodes + [alice],
                   lambda: all(n.uniqueness_provider.committed_count == 2
                               for n in nodes))
    finally:
        for n in survivors:
            n.stop()


def test_log_compaction_and_snapshot_install(tmp_path, monkeypatch):
    """DistributedImmutableMap snapshot/install capability: after the log is
    compacted, a member that LOST ITS DISK rejoins via a state snapshot from
    the leader — not log replay — and converges to the same committed map."""
    from corda_tpu.node.services.raft import RaftMember

    monkeypatch.setattr(RaftMember, "COMPACT_THRESHOLD", 8)
    nodes = make_cluster(tmp_path)
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "netmap.json")).start()
    everyone = nodes + [alice]
    try:
        leader = wait_for_leader(nodes)
        for n in everyone:
            n.refresh_netmap()

        # Enough commits to trip compaction on every member.
        for i in range(20):
            stx = issue_and_move(alice, leader.identity, magic=100 + i)
            h = alice.start_flow(NotaryClientFlow(stx))
            pump_until(everyone, lambda: h.result.done)
            h.result.result()
        pump_until(everyone, lambda: all(
            n.uniqueness_provider.committed_count == 20 for n in nodes))
        pump_until(everyone, lambda: all(
            n.raft_member.snapshot_index > 0 for n in nodes), timeout=20.0)
        for n in nodes:
            (log_len,) = n.db.conn.execute(
                "SELECT COUNT(*) FROM raft_log").fetchone()
            assert log_len <= 8 + 2  # compacted

        # Disaster: one FOLLOWER loses its entire disk.
        leader = wait_for_leader(nodes)
        victim = next(n for n in nodes if n.raft_member.role != "leader")
        name = victim.config.name
        victim.stop()
        nodes.remove(victim)
        everyone.remove(victim)
        import shutil

        shutil.rmtree(tmp_path / name)  # nothing left to replay from

        reborn = Node(NodeConfig(
            name=name, base_dir=tmp_path / name, notary="raft-simple",
            raft_cluster=CLUSTER,
            network_map=tmp_path / "netmap.json")).start()
        nodes.append(reborn)
        everyone.append(reborn)
        for n in everyone:
            n.refresh_netmap()
        # The leader's log no longer reaches index 1: only an InstallSnapshot
        # can catch the blank member up.
        pump_until(everyone, lambda:
                   reborn.uniqueness_provider.committed_count == 20,
                   timeout=25.0)
        assert reborn.raft_member.snapshot_index >= \
            min(n.raft_member.snapshot_index for n in nodes if n is not reborn)

        # And the cluster still commits new transactions afterwards.
        stx = issue_and_move(alice, leader.identity, magic=999)
        h = alice.start_flow(NotaryClientFlow(stx))
        pump_until(everyone, lambda: h.result.done, timeout=20.0)
        h.result.result()
    finally:
        for n in everyone:
            n.stop()


def test_chunked_snapshot_and_dead_peer_compaction(tmp_path, monkeypatch):
    """Bounded-log + chunked-install regressions: compaction proceeds even
    with a member DOWN (a dead peer cannot pin the log), and the snapshot
    arrives as multiple ordered chunks when the map exceeds the chunk size."""
    from corda_tpu.node.services.raft import RaftMember

    monkeypatch.setattr(RaftMember, "COMPACT_THRESHOLD", 4)
    monkeypatch.setattr(RaftMember, "SNAPSHOT_CHUNK", 3)  # force chunking
    nodes = make_cluster(tmp_path)
    alice = Node(NodeConfig(name="Alice", base_dir=tmp_path / "Alice",
                            network_map=tmp_path / "netmap.json")).start()
    everyone = nodes + [alice]
    try:
        leader = wait_for_leader(nodes)
        for n in everyone:
            n.refresh_netmap()

        # Take a member down; the survivors keep committing AND compacting.
        victim = next(n for n in nodes if n.raft_member.role != "leader")
        name = victim.config.name
        victim.stop()
        nodes.remove(victim)
        everyone.remove(victim)

        for i in range(24):
            stx = issue_and_move(alice, leader.identity, magic=300 + i)
            h = alice.start_flow(NotaryClientFlow(stx))
            # 60 s, not 20: with the aggressive compaction parameters above
            # and the sequential test scheduler, the 2-member cluster can
            # drop into an election-churn episode that takes up to ~25 s to
            # self-heal (commit window + redelivery backoff). The assertions
            # under test are about COMPACTION correctness; the wide window
            # keeps them from doubling as a tight liveness-latency test.
            pump_until(everyone, lambda: h.result.done, timeout=60.0)
            h.result.result()
        live = [n for n in nodes]
        pump_until(everyone, lambda: all(
            n.raft_member.snapshot_index > 0 for n in live), timeout=20.0)
        for n in live:
            (log_len,) = n.db.conn.execute(
                "SELECT COUNT(*) FROM raft_log").fetchone()
            # Dead-peer floor: retention is bounded by ~4x threshold + tail.
            assert log_len <= 4 * 4 + 4 + 2

        # The dead member returns (old disk intact but far behind): it can
        # only catch up through a chunked snapshot (24 entries > chunk 3).
        reborn = Node(NodeConfig(
            name=name, base_dir=tmp_path / name, notary="raft-simple",
            raft_cluster=CLUSTER,
            network_map=tmp_path / "netmap.json")).start()
        nodes.append(reborn)
        everyone.append(reborn)
        for n in everyone:
            n.refresh_netmap()
        pump_until(everyone, lambda:
                   reborn.uniqueness_provider.committed_count == 24,
                   timeout=25.0)
    finally:
        for n in everyone:
            n.stop()


def test_commit_timeout_reports_retryable_unavailable():
    # A consensus window elapsing says nothing about the transaction: the
    # client must receive the RETRYABLE NotaryUnavailable error, never
    # NotaryTransactionInvalid (which would tell it to abandon a good tx).
    from corda_tpu.flows.notary import (
        NotaryClientFlow,
        NotaryException,
        NotaryUnavailable,
    )
    from corda_tpu.node.services.raft import CommitTimeoutException
    from corda_tpu.testing.mock_network import MockNetwork
    from corda_tpu.testing.dummies import DummyContract

    import pytest

    net = MockNetwork()
    try:
        notary = net.create_notary_node("Notary", validating=False)
        alice = net.create_node("Alice")

        class TimingOutProvider:
            def commit(self, states, tx_id, caller):
                raise CommitTimeoutException(
                    "raft commit not decided within 25.0s (leader: None)")

        notary.notary_service.uniqueness_provider = TimingOutProvider()

        builder = DummyContract.generate_initial(
            alice.identity.ref(b"\x01"), 1, notary.identity)
        builder.sign_with(alice.key)
        issue = builder.to_signed_transaction()
        alice.record_transaction(issue)
        move = DummyContract.move(issue.tx.out_ref(0),
                                  alice.identity.owning_key)
        move.sign_with(alice.key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        h = alice.start_flow(NotaryClientFlow(stx))
        net.run_network()
        with pytest.raises(NotaryException) as exc:
            h.result.result()
        assert isinstance(exc.value.error, NotaryUnavailable)
        assert "not decided" in exc.value.error.reason
    finally:
        net.stop_nodes()


def test_finality_retries_through_transient_unavailability():
    # NotaryUnavailable is RETRYABLE and FinalityFlow acts on it: a notary
    # whose commit window lapses twice (degraded cluster) then recovers
    # still finalises the transaction without caller involvement.
    from corda_tpu.flows.finality import FinalityFlow
    from corda_tpu.node.services.raft import CommitTimeoutException
    from corda_tpu.testing.mock_network import MockNetwork
    from corda_tpu.testing.dummies import DummyContract

    net = MockNetwork()
    try:
        notary = net.create_notary_node("Notary", validating=False)
        alice = net.create_node("Alice")

        real_provider = notary.notary_service.uniqueness_provider
        calls = {"n": 0}

        class FlakyProvider:
            def commit(self, states, tx_id, caller):
                calls["n"] += 1
                if calls["n"] <= 2:
                    raise CommitTimeoutException("no quorum")
                return real_provider.commit(states, tx_id, caller)

        notary.notary_service.uniqueness_provider = FlakyProvider()

        builder = DummyContract.generate_initial(
            alice.identity.ref(b"\x01"), 1, notary.identity)
        builder.sign_with(alice.key)
        issue = builder.to_signed_transaction()
        alice.record_transaction(issue)
        move = DummyContract.move(issue.tx.out_ref(0),
                                  alice.identity.owning_key)
        move.sign_with(alice.key)
        stx = move.to_signed_transaction(check_sufficient_signatures=False)

        h = alice.start_flow(FinalityFlow(stx, (alice.identity,)))
        net.run_network()
        final = h.result.result()  # two failures + one success = finalised
        assert calls["n"] == 3
        assert any(s.by in notary.identity.owning_key.keys
                   for s in final.sigs)
    finally:
        net.stop_nodes()
