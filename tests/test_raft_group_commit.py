"""Commit pipeline: group commit, pipelined replication, coalesced replies.

Unit-level coverage of the ARCHITECTURE.md "Commit pipeline" contract over
an in-process message router (no sockets, no Node processes):

* a leader's round of submissions seals into ONE PutAllBatch log entry,
  with per-request conflict isolation inside the batch;
* commands buffered when leadership is lost mid-batch bounce back and
  recommit in order through the new leader (forward + reply coalescing);
* a redelivered ClientReplyBatch is absorbed idempotently;
* the pipelined broadcast streams a long tail once, in bounded chunks,
  with probe heartbeats once the window is full;
* hint-less AppendReply failures back next_index off exponentially;
* [raft] group_commit=false preserves the per-command sync path;
* _Outbox.append_many is atomic across a crash between the executemany
  and the commit durability point (full replay, never a prefix).
"""

import json
import types

from corda_tpu.contracts.structures import StateRef
from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.node.config import RaftConfig
from corda_tpu.node.messaging.tcp import _Outbox
from corda_tpu.node.services.persistence import NodeDatabase
from corda_tpu.node.services.raft import (
    AppendEntries,
    AppendReply,
    ClientReply,
    ClientReplyBatch,
    PutAllBatch,
    PutAllCommand,
    RaftMember,
    make_apply_command,
)
from corda_tpu.serialization.codec import deserialize, serialize

PARTY = Party("Client", KeyPair.generate(b"\x01" * 32).public.composite)


def cmd(ref_seed: bytes, tx_seed: bytes, rid: bytes) -> PutAllCommand:
    ref = StateRef(SecureHash.sha256(ref_seed), 0)
    return PutAllCommand((ref,), SecureHash.sha256(tx_seed), PARTY, rid)


class Net:
    """Synchronous in-process router: member name IS its address."""

    def __init__(self):
        self.handlers = {}
        self.queue = []

    def deliver_all(self):
        while self.queue:
            to, data, sender = self.queue.pop(0)
            handler = self.handlers.get(to)
            if handler is not None:
                handler(types.SimpleNamespace(data=data, sender=sender))


class FakeMessaging:
    def __init__(self, net: Net, addr: str):
        self.net, self.addr = net, addr
        self.sent = []  # (to, frame_bytes) — for wire-shape assertions

    def add_message_handler(self, topic, session_id, callback):
        self.net.handlers[self.addr] = callback

    def send(self, topic_session, data, to):
        self.sent.append((to, data))
        self.net.queue.append((to, data, self.addr))


def make_member(tmp_path, net, name, peers, clock, config=None):
    db = NodeDatabase(tmp_path / f"{name}.db")
    return RaftMember(name, peers, FakeMessaging(net, name), db,
                      make_apply_command(db), clock=clock, config=config)


def make_trio(tmp_path, net, clock, config=None):
    names = ("A", "B", "C")
    return {n: make_member(tmp_path, net, n,
                           {p: p for p in names if p != n}, clock, config)
            for n in names}


def elect(net, member, t):
    t[0] += 100.0  # past any election deadline; only `member` is ticked
    member.tick()
    net.deliver_all()  # votes out, replies back, victory broadcast handled
    assert member.role == "leader"


def settle(net, members, rounds=6):
    """Drive every member's round loop to quiescence. With the pipelined
    commit plane (round 18) state-apply runs on each member's executor
    thread, so each round must also quiesce the apply queues — and then
    deliver again, because draining results is what emits the coalesced
    ClientReply frames."""
    for _ in range(rounds):
        for m in members:
            m.flush_appends()
        net.deliver_all()
        for m in members:
            m.quiesce_apply()
        net.deliver_all()


def test_group_commit_seals_one_entry_with_conflict_isolation(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    leader = members["A"]
    elect(net, leader, t)

    # Two commands race for the same state ref; a third is independent.
    shared = StateRef(SecureHash.sha256(b"shared"), 0)
    c1 = PutAllCommand((shared,), SecureHash.sha256(b"tx1"), PARTY, b"r1")
    c2 = PutAllCommand((shared,), SecureHash.sha256(b"tx2"), PARTY, b"r2")
    c3 = cmd(b"free", b"tx3", b"r3")
    for c in (c1, c2, c3):
        leader.submit(c)
    (log_before,) = leader.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log").fetchone()
    leader.flush_appends()
    (log_after,) = leader.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log").fetchone()
    assert log_after == log_before + 1  # the whole round is ONE log entry
    (blob,) = leader.db.conn.execute(
        "SELECT blob FROM raft_log ORDER BY idx DESC LIMIT 1").fetchone()
    entry = deserialize(bytes(blob))
    assert isinstance(entry, PutAllBatch)
    assert [c.request_id for c in entry.commands] == [b"r1", b"r2", b"r3"]

    settle(net, members.values())
    # Per-request conflict isolation: the loser rejects ALONE.
    assert leader.decided[b"r1"].ok is True
    assert leader.decided[b"r2"].ok is False
    assert leader.decided[b"r2"].conflict is not None
    assert leader.decided[b"r3"].ok is True
    # Batched apply replicated identically on every member.
    for m in members.values():
        assert m.last_applied == leader.last_applied
        (n,) = m.db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        assert n == 2  # shared (first committer) + free

    stamp = leader.stamp()
    assert stamp["group_commits"] == 1
    assert stamp["group_commands"] == 3
    assert stamp["entries_per_batch"] == 3.0
    assert stamp["replication_rtt_ms_avg"] is not None
    json.dumps(stamp)  # the node_metrics contract: plain JSON types only


def test_leader_change_mid_batch_bounces_then_recommits(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    old = members["A"]
    elect(net, old, t)

    c1, c2 = cmd(b"s1", b"t1", b"r1"), cmd(b"s2", b"t2", b"r2")
    old.submit(c1)
    old.submit(c2)
    assert len(old._pending_batch) == 2

    # A higher term arrives before the round flushes: the buffered commands
    # were never sealed, so they must bounce (ok=False), not linger.
    old._become_follower(old.term + 1, leader="B")
    assert old._pending_batch == [] and not old._appending
    for rid in (b"r1", b"r2"):
        assert old.decided[rid].ok is False
        assert old.decided[rid].conflict is None  # retryable, not a conflict
    (log_len,) = old.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log").fetchone()
    assert log_len == 0  # nothing half-sealed survived the change

    # The client resubmits through the deposed member; the round's commands
    # forward to the new leader as ONE ClientCommitBatch and commit in the
    # submission order.
    new = members["B"]
    elect(net, new, t)
    old.decided.clear()
    old.submit(c1)
    old.submit(c2)
    old.flush_appends()
    settle(net, members.values())
    assert old.decided[b"r1"].ok is True
    assert old.decided[b"r2"].ok is True
    assert old.metrics["forward_frames"] == 1
    assert old.metrics["forward_commands"] == 2
    # The decisions came back coalesced: one multi-outcome frame.
    assert new.metrics["reply_frames"] == 1
    assert new.metrics["reply_commands"] == 2
    batches = [deserialize(f) for _to, f in new.messaging.sent
               if isinstance(deserialize(f), ClientReplyBatch)]
    assert len(batches) == 1
    assert {r.request_id for r in batches[0].replies} == {b"r1", b"r2"}
    # Order across the leader change: batch order == resubmission order.
    (blob,) = new.db.conn.execute(
        "SELECT blob FROM raft_log ORDER BY idx DESC LIMIT 1").fetchone()
    entry = deserialize(bytes(blob))
    assert [c.request_id for c in entry.commands] == [b"r1", b"r2"]


def test_reply_batch_redelivery_is_idempotent(tmp_path):
    net = Net()
    member = make_member(tmp_path, net, "A", {}, lambda: 0.0)
    batch = serialize(ClientReplyBatch((
        ClientReply(b"r1", True, None, "A"),
        ClientReply(b"r2", False, None, "A")))).bytes
    deliver = lambda: member._on_message(  # noqa: E731
        types.SimpleNamespace(data=batch, sender="X"))

    deliver()
    first = dict(member.decided)
    assert first[b"r1"].ok is True and first[b"r2"].ok is False
    # The transport is at-least-once: the SAME frame arrives again — both
    # before and after a waiting request consumed its decision.
    deliver()
    assert dict(member.decided) == first
    member.decided.pop(b"r1")  # a poll consumed its id (pops at most once)
    deliver()
    assert member.decided[b"r1"].ok is True  # re-recorded, nothing applied


def test_pipelined_broadcast_streams_tail_once_in_chunks(tmp_path):
    net, t = Net(), [0.0]
    member = make_member(
        tmp_path, net, "A", {"B": "B"}, lambda: t[0],
        config=RaftConfig(append_chunk=4, pipeline_window=8))
    # Leadership without an election dance: B never answers, so the stream
    # position is driven purely by _broadcast_append's own bookkeeping.
    member.role, member.leader_name, member.term = "leader", "A", 1
    for i in range(1, 11):
        member._log_append(i, 1, cmd(b"s%d" % i, b"t%d" % i, b"r%d" % i))
    member._next_index = {"B": 1}
    member._match_index = {"B": 0}
    member._sent_index = {"B": 0}

    def appends():
        out = []
        for _to, frame in member.messaging.sent:
            payload = deserialize(frame)
            if isinstance(payload, AppendEntries):
                out.append(payload)
        return out

    member._broadcast_append()
    member._broadcast_append()
    member._broadcast_append()
    first, second, third = appends()
    # Chunked streaming: 4 + 4, then the window (8 un-acked) is full and
    # the third frame is a pure probe at the stream head — the tail is
    # NEVER re-sent wholesale per tick.
    assert (first.prev_index, len(first.entries)) == (0, 4)
    assert (second.prev_index, len(second.entries)) == (4, 4)
    assert (third.prev_index, third.entries) == (8, ())
    assert member.metrics["append_entries_sent"] == 8

    # An ack opens the window: only the UNSENT remainder streams out.
    member._on_append_reply(AppendReply(1, True, 8, "B"))
    member._broadcast_append()
    fourth = appends()[-1]
    assert (fourth.prev_index, len(fourth.entries)) == (8, 2)
    # Wire entries are the log's own encoded blobs (zero codec work): a
    # follower could insert them verbatim.
    idx9 = deserialize(fourth.entries[0][1])
    assert idx9.request_id == b"r9"


def test_hintless_append_failure_backs_off_exponentially(tmp_path):
    net = Net()
    member = make_member(tmp_path, net, "A", {"B": "B"}, lambda: 0.0)
    member.role, member.leader_name, member.term = "leader", "A", 1
    member._next_index = {"B": 100}
    member._match_index = {"B": 0}
    member._sent_index = {"B": 120}

    positions = []
    for _ in range(5):
        member._on_append_reply(AppendReply(1, False, 0, "B", hint_index=-1))
        positions.append(member._next_index["B"])
        assert member._sent_index["B"] == member._next_index["B"] - 1
    # Doubling window: O(log tail) convergence instead of decrement-by-one.
    assert positions == [99, 97, 93, 85, 69]
    assert member._backoff["B"] == 32
    # Success resets the backoff (and the stream floor follows the match).
    member._on_append_reply(AppendReply(1, True, 98, "B"))
    assert "B" not in member._backoff
    assert member._next_index["B"] == 99
    # The cap: however long the divergence, a single step never exceeds
    # the append chunk.
    member._next_index["B"] = 10_000
    member._sent_index["B"] = 9_999
    for _ in range(20):
        member._on_append_reply(AppendReply(1, False, 0, "B", hint_index=-1))
    assert member._backoff["B"] == member.config.append_chunk == 256


def test_group_commit_off_keeps_per_command_sync_path(tmp_path):
    net, t = Net(), [0.0]
    member = make_member(tmp_path, net, "A", {}, lambda: t[0],
                         config=RaftConfig(group_commit=False))
    elect(net, member, t)
    for i in range(3):
        member.submit(cmd(b"s%d" % i, b"t%d" % i, b"r%d" % i))
    # Sync path: every submission appended its OWN log entry immediately.
    rows = member.db.conn.execute(
        "SELECT blob FROM raft_log ORDER BY idx").fetchall()
    assert len(rows) == 3
    assert all(isinstance(deserialize(bytes(b)), PutAllCommand)
               for (b,) in rows)
    member.flush_appends()
    member.quiesce_apply()
    for i in range(3):
        assert member.decided[b"r%d" % i].ok is True
    stamp = member.stamp()
    assert stamp["group_commit"] is False
    assert stamp["group_commits"] == 0


def test_single_member_group_commit_and_stamp(tmp_path):
    # peers={} is a quorum of one: the full submit -> seal -> commit ->
    # apply pipeline runs in-process (the shape the bench guard test and
    # any smoke harness lean on).
    net, t = Net(), [0.0]
    member = make_member(tmp_path, net, "A", {}, lambda: t[0])
    elect(net, member, t)
    for i in range(4):
        member.submit(cmd(b"s%d" % i, b"t%d" % i, b"r%d" % i))
    member.flush_appends()
    member.quiesce_apply()
    assert all(member.decided[b"r%d" % i].ok for i in range(4))
    stamp = member.stamp()
    assert stamp["entries_per_batch"] == 4.0
    assert stamp["role"] == "leader"
    json.dumps(stamp)


def test_node_metrics_carries_raft_and_transport_stamps(tmp_path):
    # End-to-end rpc wiring: a REAL raft node (cluster of one, TCP
    # transport) exports both commit-pipeline stamp dicts via node_metrics
    # — the exact path loadtest's _member_stamp reads over RPC.
    import time

    from corda_tpu.node.config import NodeConfig
    from corda_tpu.node.node import Node
    from corda_tpu.node.rpc import NodeRpcOps

    node = Node(NodeConfig(name="Solo", base_dir=tmp_path / "Solo",
                           notary="raft-simple", raft_cluster=("Solo",),
                           network_map=tmp_path / "netmap.json")).start()
    try:
        deadline = time.monotonic() + 10.0
        while node.raft_member.role != "leader":
            node.run_once(timeout=0.005)
            assert time.monotonic() < deadline, "no leader"
        metrics = NodeRpcOps(node).node_metrics()
        assert metrics["raft"]["role"] == "leader"
        assert metrics["raft"]["group_commit"] is True
        assert "entries_per_batch" in metrics["raft"]
        assert "outbox_burst_avg" in metrics["transport"]
        json.dumps(metrics["raft"])
        json.dumps(metrics["transport"])
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# Pipelined commit plane (round 18): overlapped rounds, detached apply
# executor, bounded-queue backpressure, serial-path bit-parity.
# ---------------------------------------------------------------------------


def _ledger_rows(member):
    return [(bytes(r[0]), bytes(r[1]), r[2]) for r in member.db.conn.execute(
        "SELECT state_ref, consuming, crc FROM committed_states "
        "ORDER BY state_ref").fetchall()]


def test_pipeline_off_serial_path_bit_identical(tmp_path):
    """[raft] pipeline=false preserves the serial apply path, and the
    pipelined plane (executor + columnar commit_many) produces the SAME
    bytes: identical decided outcomes per request AND identical
    committed_states rows — state_ref, consuming blob and CRC32C all
    bit-for-bit, conflicts included."""
    outcomes, ledgers = {}, {}
    for label, config in (("serial", RaftConfig(pipeline=False)),
                          ("pipelined", RaftConfig())):
        net, t = Net(), [0.0]
        member = make_member(tmp_path, net, f"A{label}", {}, lambda: t[0],
                             config=config)
        elect(net, member, t)
        shared = StateRef(SecureHash.sha256(b"dup"), 0)
        batch = [
            PutAllCommand((shared,), SecureHash.sha256(b"w1"), PARTY, b"p1"),
            PutAllCommand((shared,), SecureHash.sha256(b"w2"), PARTY, b"p2"),
            cmd(b"f1", b"w3", b"p3"),
            cmd(b"f2", b"w4", b"p4"),
        ]
        for c in batch:
            member.submit(c)
        member.flush_appends()
        member.quiesce_apply()
        outcomes[label] = {
            rid: (member.decided[rid].ok,
                  member.decided[rid].conflict is not None)
            for rid in (b"p1", b"p2", b"p3", b"p4")}
        ledgers[label] = _ledger_rows(member)
        stamp = member.stamp()
        assert stamp["pipeline"] is (label == "pipelined")
        if label == "pipelined":
            assert stamp["apply_batches"] >= 1
            assert stamp["apply_backlog"] == 0
        json.dumps(stamp)
    assert outcomes["serial"] == outcomes["pipelined"]
    assert outcomes["serial"][b"p1"] == (True, False)
    assert outcomes["serial"][b"p2"] == (False, True)  # conflict isolated
    assert ledgers["serial"] == ledgers["pipelined"]


def test_midround_seal_overlaps_replicating_round(tmp_path):
    """Pipelined rounds: a full append_chunk of buffered commands seals
    and broadcasts MID-ROUND — round N+1's entry enters the log (and the
    per-peer stream) while round N's entries are still un-acked."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0],
                        config=RaftConfig(append_chunk=2, pipeline_window=64))
    leader = members["A"]
    elect(net, leader, t)
    for i in range(5):
        leader.submit(cmd(b"s%d" % i, b"t%d" % i, b"r%d" % i))
    # append_chunk=2: submissions 2 and 4 sealed their rounds mid-flight;
    # nothing has been delivered, so BOTH sealed entries are ahead of the
    # commit index — the overlap the serial loop never had.
    assert leader.metrics["midround_seals"] == 2
    (log_len,) = leader.db.conn.execute(
        "SELECT COUNT(*) FROM raft_log").fetchone()
    assert log_len == 2 and leader.commit_index == 0
    leader.flush_appends()  # the round closes: the tail (r4) seals too
    settle(net, members.values())
    for i in range(5):
        assert leader.decided[b"r%d" % i].ok is True
    for m in members.values():
        assert m.last_applied == leader.last_applied
        (n,) = m.db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        assert n == 5
    assert leader.stamp()["midround_seals"] == 2


def test_leader_kill_mid_overlap_commits_exactly_once(tmp_path):
    """Leader dies with round N replicated-but-unacked and round N+1
    sealed right behind it. The survivors elect a new leader holding both
    entries; the clients' resubmissions ride the new leader as DUPLICATE
    log entries — and the apply plane's request/tx idempotence (same-tx
    re-commit is success, INSERT OR IGNORE) keeps the ledger exactly-once:
    one consuming row per state ref."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])
    old = members["A"]
    elect(net, old, t)
    c1, c2 = cmd(b"s1", b"t1", b"r1"), cmd(b"s2", b"t2", b"r2")
    old.submit(c1)
    old.flush_appends()   # round N sealed + broadcast, acks in flight
    old.submit(c2)
    old.flush_appends()   # round N+1 sealed mid-overlap
    del net.handlers["A"]  # the kill: A never processes another frame
    net.deliver_all()      # followers persist both entries, acks go dark
    new = members["B"]
    elect(net, new, t)     # B leads, holding both un-committed entries
    survivors = [members["B"], members["C"]]
    # The clients' retry path resubmits through the new leader.
    new.submit(c1)
    new.submit(c2)
    new.flush_appends()
    settle(net, survivors)
    assert new.decided[b"r1"].ok is True
    assert new.decided[b"r2"].ok is True
    for m in survivors:
        rows = _ledger_rows(m)
        assert len(rows) == 2  # one consuming row per ref: exactly once
        assert len({r[0] for r in rows}) == 2
    assert _ledger_rows(survivors[0]) == _ledger_rows(survivors[1])


def test_apply_queue_backpressure_sheds_new_submissions(tmp_path):
    """Bounded commit queue at depth 1 with the executor parked inside an
    apply: NEW submissions shed with the retryable bounce (ok=False,
    conflict=None) and the provider's admission point raises
    CommitQueueFullException — while in-flight commands are never shed and
    drain to success once the executor resumes."""
    import threading

    from corda_tpu.node.services.raft import (
        CommitQueueFullException,
        RaftUniquenessProvider,
    )

    net, t = Net(), [0.0]
    member = make_member(tmp_path, net, "A", {}, lambda: t[0],
                         config=RaftConfig(apply_queue_depth=1))
    elect(net, member, t)
    started, gate = threading.Event(), threading.Event()
    orig = member.apply_command
    member._commit_many = None  # route every command through `slow`

    def slow(c):
        started.set()
        assert gate.wait(5.0)
        return orig(c)

    member.apply_command = slow
    member.submit(cmd(b"s1", b"t1", b"r1"))
    member.flush_appends()       # entry 1 enqueued; executor picks it up
    assert started.wait(5.0)     # executor parked inside the apply
    member.submit(cmd(b"s2", b"t2", b"r2"))
    member.flush_appends()       # entry 2 fills the depth-1 queue
    assert member.apply_overloaded()
    assert member.apply_backlog() == 2
    member.submit(cmd(b"s3", b"t3", b"r3"))  # shed: retryable bounce
    assert member.decided[b"r3"].ok is False
    assert member.decided[b"r3"].conflict is None
    assert member.metrics["apply_shed"] == 1
    # The provider's poll sheds NOT-in-flight (re)submissions loudly.
    provider = RaftUniquenessProvider(member, pump=lambda: None)
    poll = provider.commit_async(
        (StateRef(SecureHash.sha256(b"s4"), 0),),
        SecureHash.sha256(b"t4"), PARTY)
    try:
        poll()
        raise AssertionError("expected CommitQueueFullException")
    except CommitQueueFullException:
        pass
    gate.set()                   # executor resumes: committed work drains
    member.quiesce_apply()
    assert member.decided[b"r1"].ok is True
    assert member.decided[b"r2"].ok is True
    stamp = member.stamp()
    assert stamp["apply_shed"] == 1
    assert stamp["apply_queue_depth"] == 1
    json.dumps(stamp)


def test_commit_queue_full_maps_to_retryable_overload_error(tmp_path):
    """The notary flow surfaces CommitQueueFullException as the SAME
    retryable OverloadedError the QoS admission plane uses (lane
    "commit"), so notarise_with_retry's shed-backoff handling covers the
    pipelined apply executor's admission point too."""
    from corda_tpu.flows.notary import (
        NotaryException,
        NotaryServiceFlow,
        OverloadedError,
    )
    from corda_tpu.node.services.raft import CommitQueueFullException

    class FullProvider:  # sync provider shape: no commit_async attr
        def commit(self, states, tx_id, caller):
            raise CommitQueueFullException("commit queue full")

    flow = NotaryServiceFlow.__new__(NotaryServiceFlow)
    flow.service = types.SimpleNamespace(uniqueness_provider=FullProvider())
    wtx = types.SimpleNamespace(inputs=(), id=SecureHash.sha256(b"tx"))
    try:
        list(flow._commit_input_states(wtx, PARTY))
        raise AssertionError("expected NotaryException")
    except NotaryException as e:
        assert isinstance(e.error, OverloadedError)
        assert e.error.lane == "commit"
        assert e.error.retry_after_ms == CommitQueueFullException.RETRY_AFTER_MS


def test_executor_crash_resets_and_reapplies_idempotently(tmp_path):
    """An apply exception on the executor surfaces on the consensus
    thread exactly like the serial path's, the executor resets, and the
    failed entry re-applies idempotently from the durable log through a
    fresh executor — no decision lost, no double-commit."""
    net, t = Net(), [0.0]
    member = make_member(tmp_path, net, "A", {}, lambda: t[0])
    elect(net, member, t)
    orig = member.apply_command
    member._commit_many = None
    boom = {"armed": True}

    def flaky(c):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("disk hiccup")
        return orig(c)

    member.apply_command = flaky
    member.submit(cmd(b"s1", b"t1", b"r1"))
    member.flush_appends()
    try:
        member.quiesce_apply()
        raise AssertionError("expected the executor's error to surface")
    except RuntimeError:
        pass
    assert member._apply_queue is None  # reset: fresh executor next tick
    assert member.last_applied == 0
    member.tick()  # re-enqueues the committed entry
    member.quiesce_apply()
    assert member.decided[b"r1"].ok is True
    assert member.last_applied == 1
    assert len(_ledger_rows(member)) == 1


def test_sustained_pipelined_load_serializes_settings_writes(tmp_path):
    """Sustained load with the executor genuinely concurrent: the
    consensus thread folds results (raft_commit_index/raft_last_applied
    settings writes) while the executor is mid-transaction applying the
    NEXT entry on the SAME sqlite connection. Before those writes went
    under db.lock this raced into `cannot start a transaction within a
    transaction` within a few rounds — and throughput is the acceptance
    number: the pipelined plane must clear 2k committed tx/s per group."""
    import time as _wall

    net, t = Net(), [0.0]
    member = make_member(tmp_path, net, "A", {}, lambda: t[0])
    elect(net, member, t)
    n = 4096
    t0 = _wall.perf_counter()
    for i in range(n):
        member.submit(cmd(b"s%05d" % i, b"t%05d" % i, b"r%05d" % i))
        if i % 128 == 127:
            member.flush_appends()
    member.flush_appends()
    member.quiesce_apply()
    dt = _wall.perf_counter() - t0
    assert member.last_applied == member.commit_index
    assert len(_ledger_rows(member)) == n  # every command exactly once
    assert member.metrics["apply_batches"] >= 1
    # Durable watermarks match memory after the fold.
    assert member.db.get_setting("raft_last_applied") == str(
        member.last_applied)
    # ~9k tx/s on the CI container; 2000 leaves slack for slow runners
    # while still failing hard if the plane ever re-serializes.
    assert n / dt > 2000, f"pipelined commit plane at {n / dt:.0f} tx/s"


def test_append_many_crash_consistency_full_replay(tmp_path):
    frames = [(b"id%d" % i, b"frame%d" % i) for i in range(5)]

    # Crash between the executemany and the commit durability point: the
    # rows are in the connection's open transaction but never durable.
    db = NodeDatabase(tmp_path / "n.db")
    outbox = _Outbox(db)
    real_commit = db.commit
    db.commit = lambda: (_ for _ in ()).throw(RuntimeError("power cut"))
    try:
        outbox.append_many("peer", frames)
    except RuntimeError:
        pass
    db.commit = real_commit
    db.conn.rollback()  # what process death does to an open transaction
    db.close()

    reopened = NodeDatabase(tmp_path / "n.db")
    (n,) = reopened.conn.execute(
        "SELECT COUNT(*) FROM outbox").fetchone()
    assert n == 0  # never a prefix: the whole burst rolled back

    # The caller's at-least-once resend replays the burst IN FULL.
    outbox2 = _Outbox(reopened)
    outbox2.append_many("peer", frames)
    pending = outbox2.pending("peer")
    assert [u for _s, u, _f in pending] == [u for u, _f in frames]
    assert outbox2.stats["bursts"] == 1
    assert outbox2.stats["burst_frames"] == 5
    assert outbox2.stats["max_burst"] == 5
    reopened.close()
