"""Pre-vote + check-quorum hardening (partition plane, round 20).

Unit tier over the in-process router (no sockets): the pre-vote canvass
(Raft §9.6) persists NOTHING — a disturbed member that cannot win a real
election never inflates its term or deposes a live leader; check-quorum
makes a leader that lost its majority cede instead of serving a
minority. With ``prevote=False`` (the default) none of the new frames
exist on the wire and the election path is byte-for-byte the old one.
"""

import os
import sys

from corda_tpu.node.config import RaftConfig
from corda_tpu.node.services.raft import PreVote, RaftMember

sys.path.insert(0, os.path.dirname(__file__))
from test_raft_group_commit import (  # noqa: E402
    Net,
    elect,
    make_trio,
)

PREVOTE = RaftConfig(prevote=True)


def _keep_leader_fresh(net, leader, t, steps=4, dt=0.06):
    """Advance time in sub-election steps, ticking only the leader: every
    follower's leader-contact stamp and the leader's peer-contact stamps
    stay fresh (heartbeats out, replies back)."""
    for _ in range(steps):
        t[0] += dt
        leader.tick()
        net.deliver_all()


def test_prevote_canvass_persists_nothing_and_cannot_depose(tmp_path):
    """A follower that hits its election deadline while the leader is
    LIVE (the rejoined-minority shape): its canvass is rejected by every
    peer, its term never moves, and the leader keeps its seat."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0], config=PREVOTE)
    a, b, c = members["A"], members["B"], members["C"]
    elect(net, a, t)
    _keep_leader_fresh(net, a, t)

    term_before = c.term
    c._election_deadline = t[0]  # the disturbance: deadline fires NOW
    c.tick()
    net.deliver_all()

    assert c.metrics["prevotes"] == 1  # it canvassed...
    assert c.role == "follower"        # ...but never became candidate
    assert c.term == term_before       # and persisted no new term
    assert a.role == "leader" and a.term == term_before
    # Both the live leader and the fresh-contact follower rejected it.
    assert a.metrics["prevote_rejections"] == 1
    assert b.metrics["prevote_rejections"] == 1


def test_prevote_canvass_wins_when_leader_is_gone(tmp_path):
    """Stale leader contact everywhere -> the canvass is granted, and
    only THEN does a real (term-persisting) election run and win."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0], config=PREVOTE)
    a, b = members["A"], members["B"]
    elect(net, a, t)
    _keep_leader_fresh(net, a, t)

    # The leader falls silent: contact stamps age past the stickiness
    # window with nobody heartbeating.
    t[0] += 1.0
    term_before = b.term
    b._election_deadline = t[0]
    b.tick()
    net.deliver_all()

    assert b.role == "leader"
    assert b.metrics["prevotes"] == 1
    assert b.metrics["elections_won"] == 1
    # One canvass (term untouched) + one real election (term + 1).
    assert b.term == term_before + 1


def test_checkquorum_leader_without_majority_steps_down(tmp_path):
    """A leader whose peer-contact stamps all age out cedes leadership
    instead of serving a minority partition."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0], config=PREVOTE)
    a = members["A"]
    elect(net, a, t)
    _keep_leader_fresh(net, a, t)

    t[0] += 100.0  # every peer reply is now ancient: quorum lost
    a.tick()

    assert a.role == "follower"
    assert a.leader_name is None  # stops advertising itself via hints
    assert a.metrics["checkquorum_stepdowns"] == 1
    assert a.metrics["leader_stepdowns"] == 1


def test_prevote_grant_requires_up_to_date_log(tmp_path):
    """A canvasser whose log is BEHIND is rejected even with no live
    leader — same up-to-date rule as a real vote (§5.4.1)."""
    from test_raft_group_commit import cmd, settle

    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0], config=PREVOTE)
    a, b = members["A"], members["B"]
    elect(net, a, t)
    a.submit(cmd(b"ref", b"tx", b"r1"))  # B's log gains a real entry
    settle(net, members.values())
    t[0] += 1.0  # leader contact stale: liveness cannot be the reason

    behind = PreVote(b.term + 1, "C", last_log_index=0, last_log_term=0)
    rejections = b.metrics["prevote_rejections"]
    b._on_prevote(behind, "C")
    assert b.metrics["prevote_rejections"] == rejections + 1


def test_prevote_off_keeps_the_old_election_path(tmp_path):
    """Default config: a fired deadline starts a REAL election at once
    (term persists immediately), no PreVote frame ever hits the wire,
    and a quorumless leader never self-demotes."""
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0])  # prevote=False
    a, c = members["A"], members["C"]
    elect(net, a, t)

    term_before = c.term
    c._election_deadline = t[0]
    c.tick()  # don't deliver: inspect the raw outbound frames
    assert c.role == "candidate"  # straight to candidacy...
    assert c.term == term_before + 1  # ...with the term persisted
    assert c.metrics["prevotes"] == 0
    from corda_tpu.serialization.codec import deserialize

    for _to, data in c.messaging.sent:
        assert not isinstance(getattr(deserialize(data), "payload",
                                      deserialize(data)), PreVote)
    net.deliver_all()

    t[0] += 100.0  # ancient peer contact — but check-quorum is off
    a.tick()
    assert a.metrics["checkquorum_stepdowns"] == 0


def test_single_member_group_never_steps_down(tmp_path):
    """A solo group is always its own quorum: check-quorum must not
    depose the only member."""
    from test_raft_group_commit import make_member

    net, t = Net(), [0.0]
    solo = make_member(tmp_path, net, "S", {}, lambda: t[0],
                       config=PREVOTE)
    t[0] += 100.0
    solo.tick()
    net.deliver_all()
    assert solo.role == "leader"
    t[0] += 100.0
    solo.tick()
    assert solo.role == "leader"
    assert solo.metrics["checkquorum_stepdowns"] == 0


def test_stamp_carries_partition_plane_counters(tmp_path):
    net, t = Net(), [0.0]
    members = make_trio(tmp_path, net, lambda: t[0], config=PREVOTE)
    a = members["A"]
    elect(net, a, t)
    stamp = a.stamp()
    assert stamp["prevote"] is True
    assert stamp["elections_won"] == 1
    for key in ("prevotes", "prevote_rejections",
                "checkquorum_stepdowns"):
        assert isinstance(stamp[key], int)
