"""Golden-vector tests for the pure-Python Ed25519 conformance oracle."""

import hashlib
import os

import pytest

from corda_tpu.crypto import ref_ed25519 as ref

# RFC 8032 §7.1 test vectors (seed, pubkey, msg, sig).
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032_sign(seed, pub, msg, sig):
    seed, pub, msg, sig = (bytes.fromhex(x) for x in (seed, pub, msg, sig))
    assert ref.public_key(seed) == pub
    assert ref.sign(seed, msg) == sig


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032_verify(seed, pub, msg, sig):
    pub, msg, sig = (bytes.fromhex(x) for x in (pub, msg, sig))
    assert ref.verify(pub, msg, sig)
    # Any single-bit flip in the signature must reject.
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not ref.verify(pub, msg, bytes(bad))
    assert not ref.verify(pub, msg + b"x", sig)


def test_cross_check_against_openssl():
    """Our signatures verify under OpenSSL and vice versa (canonical cases)."""
    pytest.importorskip(
        "cryptography",
        reason="the 'cryptography' wheel is not installed — no OpenSSL "
               "counterpart to cross-check against")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    rng_seed = hashlib.sha256(b"cross-check").digest()
    for i in range(20):
        seed = hashlib.sha256(rng_seed + bytes([i]))
        sk = Ed25519PrivateKey.from_private_bytes(seed.digest())
        msg = hashlib.sha256(bytes([i]) + b"msg").digest()  # 32-byte "tx id"
        ossl_sig = sk.sign(msg)
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        pub = sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        assert ref.public_key(seed.digest()) == pub
        assert ref.sign(seed.digest(), msg) == ossl_sig
        assert ref.verify(pub, msg, ossl_sig)


def test_malformed_inputs_reject_not_crash():
    """Malformed sig/key bytes must reject, never raise (SignedTransaction
    treats both a false and an exception as rejection)."""
    seed = os.urandom(32)
    pub = ref.public_key(seed)
    msg = b"hello"
    sig = ref.sign(seed, msg)
    assert ref.verify(pub, msg, sig)
    assert not ref.verify(pub, msg, b"")
    assert not ref.verify(pub, msg, sig[:63])
    assert not ref.verify(pub, msg, sig + b"\x00")
    assert not ref.verify(b"", msg, sig)
    assert not ref.verify(b"\xff" * 32, msg, sig)  # y = 2^255-1-ish, likely off-curve
    assert not ref.verify(pub[:31], msg, sig)


def test_s_malleability_accepted():
    """S >= L is accepted (i2p-eddsa 0.1.0 has no range check) — this is the
    documented divergence from strict RFC 8032 verifiers like OpenSSL."""
    pytest.importorskip(
        "cryptography",
        reason="the 'cryptography' wheel is not installed — the strict "
               "half of the divergence claim needs OpenSSL")
    seed = os.urandom(32)
    pub = ref.public_key(seed)
    msg = os.urandom(32)
    sig = ref.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    s_mall = s + ref.L
    assert s_mall < 2 ** 256
    sig_mall = sig[:32] + int.to_bytes(s_mall, 32, "little")
    assert ref.verify(pub, msg, sig_mall)

    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    opub = Ed25519PublicKey.from_public_bytes(pub)
    with pytest.raises(InvalidSignature):
        opub.verify(sig_mall, msg)  # OpenSSL is strict; we are ref10-faithful


def test_non_canonical_encoding_reduced_silently():
    """Decompression reduces y mod p silently (ref10 semantics): only
    y in [0, 19) has a representable non-canonical twin y+p < 2^255."""
    canonical = int.to_bytes(1, 32, "little")  # the identity point (0, 1)
    non_canonical = int.to_bytes(1 + ref.P, 32, "little")
    assert ref.decompress(canonical) == (0, 1)
    assert ref.decompress(non_canonical) == (0, 1)


def test_decompress_rejects_non_residue():
    # y=2 gives u/v a non-residue on edwards25519.
    bad = int.to_bytes(2, 32, "little")
    assert ref.decompress(bad) is None


def test_base58_roundtrip():
    from corda_tpu.crypto import base58

    for data in [b"", b"\x00", b"\x00\x00abc", os.urandom(33), b"hello world"]:
        assert base58.decode(base58.encode(data)) == data
    assert base58.encode(b"") == ""


def test_secure_hash():
    from corda_tpu.crypto import SecureHash

    h = SecureHash.sha256(b"abc")
    assert h.hex() == hashlib.sha256(b"abc").hexdigest()
    assert SecureHash.parse(h.hex()) == h
    with pytest.raises(ValueError):
        SecureHash(b"short")
    assert h.hash_concat(h).bytes == hashlib.sha256(h.bytes + h.bytes).digest()
