"""Push-style RPC streams: server-pushed change events with cursor resume.

The reference marshals rx Observables to per-client queues with handle
counters (reference: node/src/main/kotlin/net/corda/node/services/messaging/
RPCDispatcher.kt:33-60). Here the stream is pushed frames over the durable
messaging transport with ABSOLUTE cursors: a reconnecting client
re-subscribes with its last seen cursor and resumes without loss.
"""

import threading
import time

import pytest

from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.node.rpc import RpcClient

RPC_USERS = ({"username": "ops", "password": "pw", "permissions": ["ALL"]},)


@pytest.fixture()
def live_node(tmp_path):
    node = Node(NodeConfig(
        name="Push", base_dir=tmp_path / "Push",
        network_map=tmp_path / "netmap.json",
        rpc_users=RPC_USERS)).start()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            node.run_once(timeout=0.01)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    try:
        yield node
    finally:
        stop.set()
        pumper.join(timeout=2)
        node.stop()


def _start_noop_flows(client: RpcClient, n: int) -> None:
    for i in range(n):
        client.call("start_flow_dynamic", "PingSelfFlow", (i,))


def _setup_flow():
    from corda_tpu.flows.api import FlowLogic, flow_registry, register_flow

    if flow_registry.get("PingSelfFlow") is None:
        @register_flow(name="PingSelfFlow")
        class PingSelfFlow(FlowLogic):
            def __init__(self, n: int):
                self.n = n

            def call(self):
                return self.n

    return flow_registry.get("PingSelfFlow")


def _wait(predicate, timeout=10.0, client=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if client is not None:
            client.poll_push(timeout=0.05)
        if predicate():
            return True
        time.sleep(0.02)
    return False


def test_events_are_pushed_without_polling(live_node):
    _setup_flow()
    client = RpcClient(live_node.messaging.my_address, "ops", "pw")
    try:
        got: list = []
        client.subscribe_changes(lambda events, cursor: got.extend(events))
        _start_noop_flows(client, 3)
        # 3 flows x (add + remove) events arrive WITHOUT any
        # state_machine_changes poll.
        assert _wait(lambda: len(got) >= 6, client=client), got
        kinds = {e[0] for e in got}
        assert "add" in kinds and "remove" in kinds
    finally:
        client.close()


def test_reconnect_resumes_from_cursor_without_loss(live_node):
    _setup_flow()
    first = RpcClient(live_node.messaging.my_address, "ops", "pw")
    got_a: list = []
    sid = first.subscribe_changes(lambda events, cursor: got_a.extend(events))
    _start_noop_flows(first, 2)
    assert _wait(lambda: len(got_a) >= 4, client=first)
    cursor_after_a = first._push_cursor[sid]
    first.close()  # client vanishes mid-stream

    # Traffic continues while nobody is listening.
    lost_window = RpcClient(live_node.messaging.my_address, "ops", "pw")
    _start_noop_flows(lost_window, 2)
    lost_window.close()

    # A NEW client (new transport endpoint) resumes the SAME subscription
    # id from the last seen cursor: the in-between events arrive too.
    second = RpcClient(live_node.messaging.my_address, "ops", "pw")
    try:
        got_b: list = []
        second.subscribe_changes(
            lambda events, cursor: got_b.extend(events),
            subscription_id=sid, cursor=cursor_after_a)
        _start_noop_flows(second, 1)
        assert _wait(lambda: len(got_b) >= 6, client=second), got_b
        # 2 lost-window flows + 1 new flow = 6 events, no gap, no repeat
        # of the first client's 4.
        assert len([e for e in got_b if e[0] == "add"]) == 3
    finally:
        second.close()


def test_expired_subscription_stops_pushing(live_node):
    _setup_flow()
    client = RpcClient(live_node.messaging.my_address, "ops", "pw")
    try:
        got: list = []
        sid = client.subscribe_changes(
            lambda events, cursor: got.extend(events))
        # Force-expire server-side, then generate traffic: nothing arrives.
        live_node.rpc._subscriptions[sid][2] = 0.0
        _start_noop_flows(client, 1)
        assert not _wait(lambda: len(got) >= 1, timeout=1.0, client=client)
        assert sid not in live_node.rpc._subscriptions  # reaped
    finally:
        client.close()


def test_node_restart_snap_unfreezes_stream(live_node):
    # code-review finding: after a node restart the change log resets; a
    # client renewing with its old (now-ahead) cursor must snap to the new
    # head and keep streaming, not stall forever.
    from corda_tpu.node.statemachine import EventLog

    _setup_flow()
    client = RpcClient(live_node.messaging.my_address, "ops", "pw")
    try:
        got: list = []
        sid = client.subscribe_changes(lambda events, cursor: got.extend(events))
        _start_noop_flows(client, 2)
        assert _wait(lambda: len(got) >= 4, client=client)
        assert client._push_cursor[sid] >= 4

        # Simulate the restart: the server's change log starts over.
        live_node.smm.changes = EventLog()
        got.clear()
        client.subscribe_changes(lambda events, cursor: got.extend(events),
                                 subscription_id=sid)  # renew with old cursor
        assert client._push_cursor[sid] == 0  # snapped to the new head
        _start_noop_flows(client, 1)
        assert _wait(lambda: len(got) >= 2, client=client), got
    finally:
        client.close()


def test_eviction_gap_is_detected_not_silent(live_node):
    # code-review finding: events evicted server-side before the client
    # catches up must be COUNTED as a hole, not silently skipped.
    _setup_flow()
    client = RpcClient(live_node.messaging.my_address, "ops", "pw")
    try:
        got: list = []
        sid = client.subscribe_changes(lambda events, cursor: got.extend(events))
        live_node.smm.changes._keep = 4  # tiny retention window
        # Generate far more events than retention while the server pushes
        # into our (undrained, but still delivered) stream — then force a
        # hole by pretending we never saw the early frames.
        _start_noop_flows(client, 6)
        assert _wait(lambda: len(got) >= 8, client=client)
        # Replay the hole shape directly: last cursor far behind the next
        # frame's start.
        from corda_tpu.node.rpc import RpcPushEvent
        from corda_tpu.serialization.codec import serialize
        client._push_cursor[sid] = 1
        frame = RpcPushEvent(sid, 100, (("add", b"x"),))
        from corda_tpu.node.messaging.api import Message

        client._on_push(Message(topic_session=None,
                                data=serialize(frame).bytes,
                                unique_id=b"gap-frame", sender=None))
        assert client.push_gaps[sid] == 98  # 99 - 1 missing events counted
    finally:
        client.close()


def test_vault_updates_ride_the_push_stream(live_node):
    # The reference pushes vaultAndUpdates over RPC (CordaRPCOps.kt:71-76);
    # here vault updates join the same pushed change feed flow events use.
    from corda_tpu.finance import Amount
    from corda_tpu.finance.cash import Cash

    client = RpcClient(live_node.messaging.my_address, "ops", "pw")
    try:
        got: list = []
        client.subscribe_changes(lambda events, cursor: got.extend(events))
        builder = Cash.generate_issue(
            Amount(5_000, "USD"), live_node.identity.ref(b"\x01"),
            live_node.identity.owning_key, live_node.identity)
        builder.sign_with(live_node.key)
        stx = builder.to_signed_transaction()
        live_node.services.record_transactions([stx])
        assert _wait(
            lambda: any(e[0] == "vault" for e in got), client=client), got
        vault_events = [e for e in got if e[0] == "vault"]
        assert vault_events[0][1] == 0   # nothing consumed by an issue
        assert vault_events[0][2] == 1   # one state produced
    finally:
        client.close()
