"""Deterministic-sandbox tests: vetting rejections + runtime cost kills.

Mirrors the reference's sandbox test tier (reference: experimental/sandbox/
src/test/java/net/corda/sandbox — whitelist-rejection and cost-instrumented
execution checks) against real framework contracts.
"""

import time

import pytest

from corda_tpu.contracts.sandbox import (
    CostBudget,
    DeterministicSandbox,
    SandboxCostExceeded,
    SandboxViolation,
    sandboxed_verify,
)
from corda_tpu.contracts.structures import Contract, Issued
from corda_tpu.contracts.universal import UIssue
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.finance import Amount, CashState
from corda_tpu.finance.cash import Cash, CashIssue
from corda_tpu.testing.ledger_dsl import ledger

ALICE = Party.of("Alice", KeyPair.generate(b"\x51" * 32).public)
BANK = Party.of("Bank", KeyPair.generate(b"\x52" * 32).public)
NOTARY = Party.of("Notary", KeyPair.generate(b"\x53" * 32).public)
TOKEN = Issued(BANK.ref(b"\x01"), "USD")


def issue_tx():
    """A valid Cash issuance as a TransactionForContract."""
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.output("cash", CashState(Amount(1000, TOKEN), ALICE.owning_key))
        tx.command(CashIssue(1), BANK.owning_key)
        tx.verifies()
        return tx._tx_for_contract()


class TestVetting:
    def test_platform_contracts_are_suitable(self):
        sandbox = DeterministicSandbox()
        assert sandbox.is_suitable(Cash())

    def test_clock_access_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                if time.time() > 0:
                    raise ValueError("nope")

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_io_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                open("/etc/passwd").read()

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_dynamic_code_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                eval("1 + 1")

        with pytest.raises(SandboxViolation, match="eval"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nonwhitelisted_import_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                import socket
                socket.gethostname()

        with pytest.raises(SandboxViolation, match="socket"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_reflection_escape_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                (lambda: 0).__globals__["__builtins__"]

        with pytest.raises(SandboxViolation, match="__globals__"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_transitive_helper_is_vetted(self):
        def helper():
            return time.time()

        class EvilContract(Contract):
            def verify(self, tx):
                helper()

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nested_code_objects_are_vetted(self):
        class EvilContract(Contract):
            def verify(self, tx):
                def inner():
                    return open("x")
                return inner

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_getattr_escape_rejected(self):
        # getattr("__globals__") would bypass the LOAD_ATTR check entirely.
        class EvilContract(Contract):
            def verify(self, tx):
                g = getattr(self.verify, "__glo" + "bals__")
                return g

        with pytest.raises(SandboxViolation, match="getattr"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_attrgetter_escape_rejected(self):
        # operator.attrgetter('__globals__') passed static vetting while
        # `operator` was whitelisted, bypassing both the getattr ban and the
        # FORBIDDEN_ATTRS LOAD_ATTR check (round-2 advisor finding). Two
        # independent layers must now stop it: `operator` is no longer
        # whitelisted, and the reflection string constant itself fails
        # vetting.
        import operator

        class EvilContract(Contract):
            def verify(self, tx):
                getter = operator.attrgetter("__globals__")
                return getter(type(tx).verify)

        with pytest.raises(SandboxViolation):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_reflection_string_constant_rejected(self):
        # "{0.__globals__}".format(fn) reaches reflection through the
        # *allowed* format builtin; the string-constant scan fails it closed.
        class EvilContract(Contract):
            def verify(self, tx):
                return "x.__globals__"  # data smuggled to a lookup helper

        with pytest.raises(SandboxViolation, match="string constant"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_str_format_banned(self):
        # "{0.__globals__}".format(fn) does attribute traversal inside the
        # format mini-language, invisible to the LOAD_ATTR check — and the
        # string can be assembled at runtime to evade the constant scan. The
        # format attribute itself is therefore forbidden (f-strings compile
        # to real LOAD_ATTR opcodes and remain usable).
        class EvilContract(Contract):
            def verify(self, tx):
                tmpl = "".join(["{0.__glo", "bals__}"])
                return tmpl.format(type(tx).verify)

        with pytest.raises(SandboxViolation, match="format"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_failed_vet_is_not_cached(self):
        # A failed vet must not poison the vetted-cache: the same sandbox
        # re-vetting the same malicious contract must fail again, not pass.
        class EvilContract(Contract):
            def verify(self, tx):
                return open("/etc/passwd")

        sandbox = DeterministicSandbox()
        assert not sandbox.is_suitable(EvilContract())
        assert not sandbox.is_suitable(EvilContract())
        with pytest.raises(SandboxViolation, match="open"):
            sandbox.run(EvilContract.verify, EvilContract(), None)

    def test_cached_property_is_vetted(self):
        # functools is whitelisted, so a cached_property instance passes
        # the module check; its wrapped function must still be vetted.
        import functools

        class Helper:
            @functools.cached_property
            def now(self):
                return time.time()

        class EvilContract(Contract):
            def verify(self, tx):
                return Helper().now

        with pytest.raises(SandboxViolation):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_property_accessor_is_vetted(self):
        # Code smuggled in a property on a helper class previously ran
        # unvetted (round-2 advisor finding).
        class Helper:
            @property
            def now(self):
                return time.time()

        class EvilContract(Contract):
            def verify(self, tx):
                return Helper().now

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nested_class_is_vetted(self):
        class Outer:
            class Inner:
                def leak(self):
                    return open("/etc/passwd")

        class EvilContract(Contract):
            def verify(self, tx):
                return Outer.Inner().leak()

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_user_base_class_is_vetted(self):
        class EvilBase:
            def helper(self):
                return time.time()

        class Derived(EvilBase):
            pass

        class EvilContract(Contract):
            def verify(self, tx):
                return Derived().helper()

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_runtime_builtins_are_restricted(self):
        # Defense in depth: even if static vetting were bypassed, the entry
        # function executes over a restricted __builtins__ mapping.
        class Contract2(Contract):
            def verify(self, tx):
                return eval("1+1")  # noqa: S307 — the point of the test

        confined = DeterministicSandbox()._confine(Contract2.verify)
        with pytest.raises(NameError):
            confined(Contract2(), None)

    def test_global_mutation_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                global _leak
                _leak = tx  # persists across verifications

        with pytest.raises(SandboxViolation, match="global"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_attribute_mutation_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                tx.inputs = ()  # monkey-patching the tx view

        with pytest.raises(SandboxViolation, match="mutation"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nondeterministic_builtins_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                return id(tx)

        with pytest.raises(SandboxViolation, match="id"):
            DeterministicSandbox().vet_contract(EvilContract())


class TestCostAccounting:
    def test_infinite_loop_killed(self):
        def spin():
            n = 0
            while True:
                n += 1

        sandbox = DeterministicSandbox(budget=CostBudget(jumps=10_000))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(spin)
        assert e.value.kind == "jump"

    def test_call_bomb_killed(self):
        def fanout(depth=0):
            for _ in range(50):
                if depth < 50:
                    fanout(depth + 1)

        sandbox = DeterministicSandbox(budget=CostBudget(invokes=1_000))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(fanout)
        assert e.value.kind == "invoke"

    def test_allocation_bomb_killed(self):
        def hoard():
            return [bytes(1024) for _ in range(64 * 1024)]

        sandbox = DeterministicSandbox(
            budget=CostBudget(alloc_bytes=1 << 20, jumps=10**9))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(hoard)
        assert e.value.kind == "alloc"

    def test_throw_storm_killed(self):
        def storm():
            for _ in range(200):
                try:
                    raise ValueError("x")
                except ValueError:
                    pass

        sandbox = DeterministicSandbox(budget=CostBudget(throws=50))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(storm)
        assert e.value.kind == "throw"

    def test_well_behaved_contract_passes(self):
        tx = issue_tx()
        sandboxed_verify(tx)  # Cash.verify under default budgets

    def test_rejection_propagates_unchanged(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output(None, CashState(Amount(1000, TOKEN), ALICE.owning_key))
            tx.command(CashIssue(1), ALICE.owning_key)  # not the issuer
            bad = tx._tx_for_contract()
            tx.fails_with("issuer")
        with pytest.raises(Exception, match="issuer"):
            sandboxed_verify(bad)


class TestHashVetting:
    def test_user_defined_hash_is_vetted(self):
        # Round-3 advisor: __hash__ sat on the vet skip list, so a hostile
        # __hash__ ran arbitrary unvetted code the moment an instance
        # landed in a set.
        class Sneaky:
            def __hash__(self):
                open("/etc/passwd")
                return 0

        class EvilContract(Contract):
            def verify(self, tx):
                return len({Sneaky()})

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_frozen_dataclass_state_passes(self):
        # The ONE excused __hash__ shape: the dataclass-generated hash
        # (calls the otherwise-forbidden hash() builtin). Its provenance +
        # body shape are checked, not its name.
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class Pt:
            x: int

        class GoodContract(Contract):
            def verify(self, tx):
                return len({Pt(1), Pt(2)})

        DeterministicSandbox().vet_contract(GoodContract())  # must not raise

    def test_docstring_mentioning_dunder_passes(self):
        # Round-3 advisor (low): docs/error text legitimately *mention*
        # reflection names; only non-docstring string constants scan.
        class DocContract(Contract):
            def verify(self, tx):
                "a contract may not touch __dict__ here"
                return True

        DeterministicSandbox().vet_contract(DocContract())  # must not raise

    def test_non_docstring_constant_still_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                "legit docstring"
                return "x.__globals__"

        with pytest.raises(SandboxViolation, match="string constant"):
            DeterministicSandbox().vet_contract(EvilContract())


class TestTrustForgery:
    def test_forged_module_name_does_not_borrow_trust(self):
        # code-review finding: __module__ / __globals__['__name__'] are just
        # strings a hostile module body could forge before vetting runs.
        # Trust requires the function's __globals__ to BE the claimed
        # module's real sys.modules namespace.
        ns = {"__name__": "math"}
        exec("def verify(self, tx):\n    return open('/etc/passwd')", ns)
        evil_verify = ns["verify"]
        assert evil_verify.__module__ == "math"  # the forgery "took"
        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet(evil_verify)

    def test_identity_name_assignment_rejected_in_module_body(self):
        # The loader vets module bodies pre-exec; assigning __name__ there
        # is the impersonation primitive and must fail vetting.
        code = compile('__name__ = "math"\nx = 1\n', "<attachment>", "exec")
        with pytest.raises(SandboxViolation, match="identity name"):
            DeterministicSandbox()._vet_code(code, {})

    def test_class_body_module_assignment_rejected(self):
        code = compile(
            'class C:\n    __module__ = "math"\n', "<attachment>", "exec")
        with pytest.raises(SandboxViolation, match="identity name"):
            DeterministicSandbox()._vet_code(code, {})

    def test_wraps_stamped_global_function_is_still_vetted(self):
        # round-4 advisor (medium): functools is whitelisted, so
        # @functools.wraps(math.floor) stamps __module__='math' onto a user
        # function. When a contract's verify reaches it via globals,
        # _vet_value must NOT return on the bare string — the body must be
        # vetted (and here rejected for open()).
        import functools
        import math

        @functools.wraps(math.floor)
        def evil(x):
            return open("/etc/passwd")

        assert evil.__module__ == "math"  # the forgery "took"

        def verify(self, tx):
            return evil(1)

        verify.__globals__["evil"] = evil
        try:
            with pytest.raises(SandboxViolation, match="open"):
                DeterministicSandbox().vet(verify)
        finally:
            del verify.__globals__["evil"]

    def test_wraps_stamped_function_is_still_confined(self):
        # Same forgery against _confine's platform exemption: the confined
        # runtime must see restricted builtins, not the real ones.
        import functools
        import math

        @functools.wraps(math.floor)
        def probe(x):
            return __builtins__  # noqa: F821 — resolved at runtime

        confined = DeterministicSandbox()._confine(probe)
        assert confined is not probe  # not exempted as "platform"
        assert "open" not in confined(0)

    def test_forged_class_module_is_still_vetted(self):
        # The class-side forgery: type() builds a class with any __module__
        # without tripping the STORE_NAME identity check or STORE_ATTR. A
        # stamped user class must not borrow platform trust in _vet_value.
        Evil = type("Evil", (), {
            "__module__": "math",
            "attack": lambda self: open("/etc/passwd"),
        })
        assert Evil.__module__ == "math"  # the forgery "took"
        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox()._vet_value("Evil", Evil, "<test>")

    def test_genuine_platform_builtin_is_trusted(self):
        # round-4 advisor (low): builtins from whitelisted modules have no
        # __globals__, so the identity check can never pass; ownership
        # (module attribute is the function, or bound to the module) must
        # trust them instead of raising 'not vettable'.
        import math

        sandbox = DeterministicSandbox()
        sandbox.vet(math.floor)  # must not raise
        sandbox._vet_value("floor", math.floor, "<test>")

    def test_genuine_platform_class_and_instance_trusted(self):
        import decimal

        sandbox = DeterministicSandbox()
        assert sandbox._trusted_class(decimal.Decimal)
        sandbox._vet_value("D", decimal.Decimal, "<test>")
        sandbox._vet_value("d", decimal.Decimal("1.5"), "<test>")

    def test_builtin_type_alias_still_forbidden(self):
        # review finding: an ALIAS of a forbidden builtin type must not
        # launder through class-identity trust — memoryview is builtins-
        # owned, but the name screen has to fire exactly as for the
        # spelled-out name.
        with pytest.raises(SandboxViolation, match="memoryview"):
            DeterministicSandbox()._vet_value("mv", memoryview, "<test>")

    def test_partial_over_builtin_rejected(self):
        # review finding: functools.partial(open, ...) is an instance of a
        # whitelisted-module class but holds a REAL builtin confinement
        # can't strip; class identity alone must not trust instances.
        import functools

        p = functools.partial(open, "/etc/passwd")
        with pytest.raises(SandboxViolation):
            DeterministicSandbox()._vet_value("p", p, "<test>")

    def test_mutable_container_global_rejected(self):
        # review finding: a list/dict global is cross-replay mutable state;
        # the instance-trust branch must not bless builtin containers.
        for bad in ([], {}, set()):
            with pytest.raises(SandboxViolation):
                DeterministicSandbox()._vet_value("cache", bad, "<test>")

    def test_frozen_dataclass_field_payload_is_vetted(self):
        # review finding: a platform frozen dataclass with a field holding a
        # real builtin is a smuggle — trusting the instance must vet fields.
        from corda_tpu.contracts.structures import TransactionState

        smuggle = TransactionState(data=open, notary=None)
        with pytest.raises(SandboxViolation, match="X.data"):
            DeterministicSandbox()._vet_value("X", smuggle, "<test>")
        # Benign payloads still pass.
        ok = TransactionState(data=123, notary=None)
        DeterministicSandbox()._vet_value("X", ok, "<test>")

    def test_tuple_smuggling_builtin_rejected(self):
        # Same vector one level shallower: (open,)[0] from confined code.
        with pytest.raises(SandboxViolation, match=r"T\[0\]"):
            DeterministicSandbox()._vet_value("T", (open,), "<test>")
        DeterministicSandbox()._vet_value("T", (1, "a", (2.0,)), "<test>")

    def test_forged_builtins_module_instance_rejected(self):
        # review finding: forging __module__="builtins" (instead of "math")
        # must not slip a user callable instance through the old
        # string-compare builtins branch.
        Evil = type("Evil", (), {
            "__module__": "builtins",
            "__call__": lambda self: open("/etc/passwd"),
        })
        helper = Evil()
        sandbox = DeterministicSandbox()
        with pytest.raises(SandboxViolation):
            sandbox._vet_value("helper", helper, "<test>")
        # Genuine builtins-owned C callables still pass the identity walk.
        sandbox._vet_value("length", len, "<test>")

    def test_class_attribute_tuple_smuggle_rejected(self):
        # review finding: `T = (open,)` as a CLASS attribute must be vetted
        # element-wise exactly like a module-global tuple.
        class Carrier:
            T = (open,)

            def verify(self, tx):
                return Carrier.T[0]("/etc/passwd")

        with pytest.raises(SandboxViolation):
            DeterministicSandbox()._vet_class(Carrier, "<test>")

    def test_forged_c_callable_surface_rejected(self):
        # review finding: an instance forging __module__/__self__ as class
        # attributes must not pass _trusted_home's ownership leg — only
        # genuine C-callable types qualify.
        import math

        Evil = type("Evil", (), {
            "__module__": "math",
            "__self__": math,
            "__call__": lambda self: open("/etc/passwd"),
        })
        x = Evil()
        sandbox = DeterministicSandbox()
        assert not sandbox._trusted_home(x)
        with pytest.raises(SandboxViolation):
            sandbox._vet_value("x", x, "<test>")


class TestDataclassHash:
    def test_fieldless_frozen_dataclass_hash_excused(self):
        # round-4 advisor (low): a fieldless frozen dataclass generates
        # __hash__ with co_consts == (None, ()) — hash of the empty field
        # tuple — and must still pass the shape check.
        from dataclasses import dataclass as dc

        @dc(frozen=True)
        class Marker:
            pass

        def verify(self, tx):
            return Marker() in {Marker()}

        verify.__globals__["Marker"] = Marker
        try:
            DeterministicSandbox().vet(verify)  # must not raise
        finally:
            del verify.__globals__["Marker"]
