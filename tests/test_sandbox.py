"""Deterministic-sandbox tests: vetting rejections + runtime cost kills.

Mirrors the reference's sandbox test tier (reference: experimental/sandbox/
src/test/java/net/corda/sandbox — whitelist-rejection and cost-instrumented
execution checks) against real framework contracts.
"""

import time

import pytest

from corda_tpu.contracts.sandbox import (
    CostBudget,
    DeterministicSandbox,
    SandboxCostExceeded,
    SandboxViolation,
    sandboxed_verify,
)
from corda_tpu.contracts.structures import Contract, Issued
from corda_tpu.contracts.universal import UIssue
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.finance import Amount, CashState
from corda_tpu.finance.cash import Cash, CashIssue
from corda_tpu.testing.ledger_dsl import ledger

ALICE = Party.of("Alice", KeyPair.generate(b"\x51" * 32).public)
BANK = Party.of("Bank", KeyPair.generate(b"\x52" * 32).public)
NOTARY = Party.of("Notary", KeyPair.generate(b"\x53" * 32).public)
TOKEN = Issued(BANK.ref(b"\x01"), "USD")


def issue_tx():
    """A valid Cash issuance as a TransactionForContract."""
    l = ledger(NOTARY)
    with l.transaction() as tx:
        tx.output("cash", CashState(Amount(1000, TOKEN), ALICE.owning_key))
        tx.command(CashIssue(1), BANK.owning_key)
        tx.verifies()
        return tx._tx_for_contract()


class TestVetting:
    def test_platform_contracts_are_suitable(self):
        sandbox = DeterministicSandbox()
        assert sandbox.is_suitable(Cash())

    def test_clock_access_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                if time.time() > 0:
                    raise ValueError("nope")

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_io_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                open("/etc/passwd").read()

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_dynamic_code_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                eval("1 + 1")

        with pytest.raises(SandboxViolation, match="eval"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nonwhitelisted_import_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                import socket
                socket.gethostname()

        with pytest.raises(SandboxViolation, match="socket"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_reflection_escape_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                (lambda: 0).__globals__["__builtins__"]

        with pytest.raises(SandboxViolation, match="__globals__"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_transitive_helper_is_vetted(self):
        def helper():
            return time.time()

        class EvilContract(Contract):
            def verify(self, tx):
                helper()

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nested_code_objects_are_vetted(self):
        class EvilContract(Contract):
            def verify(self, tx):
                def inner():
                    return open("x")
                return inner

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_getattr_escape_rejected(self):
        # getattr("__globals__") would bypass the LOAD_ATTR check entirely.
        class EvilContract(Contract):
            def verify(self, tx):
                g = getattr(self.verify, "__glo" + "bals__")
                return g

        with pytest.raises(SandboxViolation, match="getattr"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_attrgetter_escape_rejected(self):
        # operator.attrgetter('__globals__') passed static vetting while
        # `operator` was whitelisted, bypassing both the getattr ban and the
        # FORBIDDEN_ATTRS LOAD_ATTR check (round-2 advisor finding). Two
        # independent layers must now stop it: `operator` is no longer
        # whitelisted, and the reflection string constant itself fails
        # vetting.
        import operator

        class EvilContract(Contract):
            def verify(self, tx):
                getter = operator.attrgetter("__globals__")
                return getter(type(tx).verify)

        with pytest.raises(SandboxViolation):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_reflection_string_constant_rejected(self):
        # "{0.__globals__}".format(fn) reaches reflection through the
        # *allowed* format builtin; the string-constant scan fails it closed.
        class EvilContract(Contract):
            def verify(self, tx):
                return "x.__globals__"  # data smuggled to a lookup helper

        with pytest.raises(SandboxViolation, match="string constant"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_str_format_banned(self):
        # "{0.__globals__}".format(fn) does attribute traversal inside the
        # format mini-language, invisible to the LOAD_ATTR check — and the
        # string can be assembled at runtime to evade the constant scan. The
        # format attribute itself is therefore forbidden (f-strings compile
        # to real LOAD_ATTR opcodes and remain usable).
        class EvilContract(Contract):
            def verify(self, tx):
                tmpl = "".join(["{0.__glo", "bals__}"])
                return tmpl.format(type(tx).verify)

        with pytest.raises(SandboxViolation, match="format"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_failed_vet_is_not_cached(self):
        # A failed vet must not poison the vetted-cache: the same sandbox
        # re-vetting the same malicious contract must fail again, not pass.
        class EvilContract(Contract):
            def verify(self, tx):
                return open("/etc/passwd")

        sandbox = DeterministicSandbox()
        assert not sandbox.is_suitable(EvilContract())
        assert not sandbox.is_suitable(EvilContract())
        with pytest.raises(SandboxViolation, match="open"):
            sandbox.run(EvilContract.verify, EvilContract(), None)

    def test_cached_property_is_vetted(self):
        # functools is whitelisted, so a cached_property instance passes
        # the module check; its wrapped function must still be vetted.
        import functools

        class Helper:
            @functools.cached_property
            def now(self):
                return time.time()

        class EvilContract(Contract):
            def verify(self, tx):
                return Helper().now

        with pytest.raises(SandboxViolation):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_property_accessor_is_vetted(self):
        # Code smuggled in a property on a helper class previously ran
        # unvetted (round-2 advisor finding).
        class Helper:
            @property
            def now(self):
                return time.time()

        class EvilContract(Contract):
            def verify(self, tx):
                return Helper().now

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nested_class_is_vetted(self):
        class Outer:
            class Inner:
                def leak(self):
                    return open("/etc/passwd")

        class EvilContract(Contract):
            def verify(self, tx):
                return Outer.Inner().leak()

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_user_base_class_is_vetted(self):
        class EvilBase:
            def helper(self):
                return time.time()

        class Derived(EvilBase):
            pass

        class EvilContract(Contract):
            def verify(self, tx):
                return Derived().helper()

        with pytest.raises(SandboxViolation, match="time"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_runtime_builtins_are_restricted(self):
        # Defense in depth: even if static vetting were bypassed, the entry
        # function executes over a restricted __builtins__ mapping.
        class Contract2(Contract):
            def verify(self, tx):
                return eval("1+1")  # noqa: S307 — the point of the test

        confined = DeterministicSandbox()._confine(Contract2.verify)
        with pytest.raises(NameError):
            confined(Contract2(), None)

    def test_global_mutation_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                global _leak
                _leak = tx  # persists across verifications

        with pytest.raises(SandboxViolation, match="global"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_attribute_mutation_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                tx.inputs = ()  # monkey-patching the tx view

        with pytest.raises(SandboxViolation, match="mutation"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_nondeterministic_builtins_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                return id(tx)

        with pytest.raises(SandboxViolation, match="id"):
            DeterministicSandbox().vet_contract(EvilContract())


class TestCostAccounting:
    def test_infinite_loop_killed(self):
        def spin():
            n = 0
            while True:
                n += 1

        sandbox = DeterministicSandbox(budget=CostBudget(jumps=10_000))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(spin)
        assert e.value.kind == "jump"

    def test_call_bomb_killed(self):
        def fanout(depth=0):
            for _ in range(50):
                if depth < 50:
                    fanout(depth + 1)

        sandbox = DeterministicSandbox(budget=CostBudget(invokes=1_000))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(fanout)
        assert e.value.kind == "invoke"

    def test_allocation_bomb_killed(self):
        def hoard():
            return [bytes(1024) for _ in range(64 * 1024)]

        sandbox = DeterministicSandbox(
            budget=CostBudget(alloc_bytes=1 << 20, jumps=10**9))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(hoard)
        assert e.value.kind == "alloc"

    def test_throw_storm_killed(self):
        def storm():
            for _ in range(200):
                try:
                    raise ValueError("x")
                except ValueError:
                    pass

        sandbox = DeterministicSandbox(budget=CostBudget(throws=50))
        with pytest.raises(SandboxCostExceeded) as e:
            sandbox.run(storm)
        assert e.value.kind == "throw"

    def test_well_behaved_contract_passes(self):
        tx = issue_tx()
        sandboxed_verify(tx)  # Cash.verify under default budgets

    def test_rejection_propagates_unchanged(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output(None, CashState(Amount(1000, TOKEN), ALICE.owning_key))
            tx.command(CashIssue(1), ALICE.owning_key)  # not the issuer
            bad = tx._tx_for_contract()
            tx.fails_with("issuer")
        with pytest.raises(Exception, match="issuer"):
            sandboxed_verify(bad)


class TestHashVetting:
    def test_user_defined_hash_is_vetted(self):
        # Round-3 advisor: __hash__ sat on the vet skip list, so a hostile
        # __hash__ ran arbitrary unvetted code the moment an instance
        # landed in a set.
        class Sneaky:
            def __hash__(self):
                open("/etc/passwd")
                return 0

        class EvilContract(Contract):
            def verify(self, tx):
                return len({Sneaky()})

        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet_contract(EvilContract())

    def test_frozen_dataclass_state_passes(self):
        # The ONE excused __hash__ shape: the dataclass-generated hash
        # (calls the otherwise-forbidden hash() builtin). Its provenance +
        # body shape are checked, not its name.
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class Pt:
            x: int

        class GoodContract(Contract):
            def verify(self, tx):
                return len({Pt(1), Pt(2)})

        DeterministicSandbox().vet_contract(GoodContract())  # must not raise

    def test_docstring_mentioning_dunder_passes(self):
        # Round-3 advisor (low): docs/error text legitimately *mention*
        # reflection names; only non-docstring string constants scan.
        class DocContract(Contract):
            def verify(self, tx):
                "a contract may not touch __dict__ here"
                return True

        DeterministicSandbox().vet_contract(DocContract())  # must not raise

    def test_non_docstring_constant_still_rejected(self):
        class EvilContract(Contract):
            def verify(self, tx):
                "legit docstring"
                return "x.__globals__"

        with pytest.raises(SandboxViolation, match="string constant"):
            DeterministicSandbox().vet_contract(EvilContract())


class TestTrustForgery:
    def test_forged_module_name_does_not_borrow_trust(self):
        # code-review finding: __module__ / __globals__['__name__'] are just
        # strings a hostile module body could forge before vetting runs.
        # Trust requires the function's __globals__ to BE the claimed
        # module's real sys.modules namespace.
        ns = {"__name__": "math"}
        exec("def verify(self, tx):\n    return open('/etc/passwd')", ns)
        evil_verify = ns["verify"]
        assert evil_verify.__module__ == "math"  # the forgery "took"
        with pytest.raises(SandboxViolation, match="open"):
            DeterministicSandbox().vet(evil_verify)

    def test_identity_name_assignment_rejected_in_module_body(self):
        # The loader vets module bodies pre-exec; assigning __name__ there
        # is the impersonation primitive and must fail vetting.
        code = compile('__name__ = "math"\nx = 1\n', "<attachment>", "exec")
        with pytest.raises(SandboxViolation, match="identity name"):
            DeterministicSandbox()._vet_code(code, {})

    def test_class_body_module_assignment_rejected(self):
        code = compile(
            'class C:\n    __module__ = "math"\n', "<attachment>", "exec")
        with pytest.raises(SandboxViolation, match="identity name"):
            DeterministicSandbox()._vet_code(code, {})
