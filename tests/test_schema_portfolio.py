"""Schema projections, the SVG visualiser, and simm-lite valuation.

Mirrors the reference's HibernateObserver/CashSchemaV1 coverage (reference:
node/.../schema/HibernateObserver.kt:28, finance/.../schemas/CashSchemaV1.kt),
network-visualiser output, and the simm-valuation-demo protocol shape
(samples/simm-valuation-demo/.../flows/SimmFlow.kt).
"""

import pytest

from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.finance import Amount, Cash
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.testing.mock_network import MockNetwork


class TestSchemaProjection:
    def test_cash_projects_and_marks_consumed(self, tmp_path):
        node = Node(NodeConfig(name="S", base_dir=tmp_path / "S",
                               network_map=tmp_path / "m.json")).start()
        try:
            issue = Cash.generate_issue(
                Amount(5000, "USD"), node.identity.ref(b"\x01"),
                node.identity.owning_key, node.identity)
            issue.sign_with(node.key)
            issue_stx = issue.to_signed_transaction()
            node.services.record_transactions([issue_stx])

            rows = node.schema.query("cash_states")
            assert len(rows) == 1
            assert rows[0]["currency"] == "USD"
            assert rows[0]["quantity"] == 5000
            assert rows[0]["consumed"] == 0

            # Spend it: the projection row flips to consumed, change appears.
            from corda_tpu.finance import CashState
            from corda_tpu.transactions.builder import TransactionBuilder

            tx = TransactionBuilder(notary=node.identity)
            Cash.generate_spend(
                tx, Amount(2000, "USD"), node.identity.owning_key,
                node.services.vault_service.unconsumed_states(CashState))
            tx.sign_with(node.key)
            node.services.record_transactions(
                [tx.to_signed_transaction(check_sufficient_signatures=False)])

            live = node.schema.query("cash_states", "consumed = 0")
            assert sum(r["quantity"] for r in live) == 5000
            spent = node.schema.query("cash_states", "consumed = 1")
            assert len(spent) == 1 and spent[0]["quantity"] == 5000

            # SQL-side filtering works (the operational-query point).
            big = node.schema.query(
                "cash_states", "consumed = 0 AND quantity >= ?", (2500,))
            assert len(big) == 1
        finally:
            node.stop()

    def test_projection_rebuilds_after_restart(self, tmp_path):
        node = Node(NodeConfig(name="S2", base_dir=tmp_path / "S2",
                               network_map=tmp_path / "m.json")).start()
        issue = Cash.generate_issue(
            Amount(77, "EUR"), node.identity.ref(b"\x01"),
            node.identity.owning_key, node.identity)
        issue.sign_with(node.key)
        node.services.record_transactions([issue.to_signed_transaction()])
        node.stop()
        del node

        reborn = Node(NodeConfig(name="S2", base_dir=tmp_path / "S2",
                                 network_map=tmp_path / "m.json")).start()
        try:
            rows = reborn.schema.query("cash_states", "consumed = 0")
            assert [r["quantity"] for r in rows] == [77]
        finally:
            reborn.stop()


class TestVisualiser:
    def test_svg_renders_simulation_feed(self, tmp_path):
        from corda_tpu.testing.simulation import TradeSimulation
        from corda_tpu.tools.visualiser import render_svg

        sim = TradeSimulation()
        try:
            sim.run_trade(500)
            out = tmp_path / "trade.svg"
            svg = render_svg(sim.sent_messages, out)
            assert out.exists()
            assert svg.startswith("<svg")
            assert "platform.session" in svg  # topic labels present
            # One lifeline per participating node.
            assert svg.count("font-weight='bold'") >= 3
        finally:
            sim.stop()


class TestSimmValuation:
    def test_both_sides_compute_and_agree(self):
        from corda_tpu.contracts.structures import Command, now_micros
        from corda_tpu.flows.oracle import FixOf, RateOracle
        from corda_tpu.tools.portfolio import (
            PortfolioState,
            SimmValuationFlow,
            ValueCommand,
            compute_valuation,
            install_simm_responder,
        )
        from corda_tpu.transactions.builder import TransactionBuilder

        net = MockNetwork(verifier=CpuVerifier())
        try:
            notary = net.create_notary_node("Notary")
            a = net.create_node("Dealer A")
            b = net.create_node("Dealer B")
            o = net.create_node("Oracle")
            rate_ref = FixOf("IM-RATE", 20_200, "1D")
            RateOracle(o.smm, o.key, {rate_ref: 2_5000})  # 2.5% (1e-2 bp)
            install_simm_responder(b.smm)

            from corda_tpu.tools.simm import IRSTrade
            trades = (IRSTrade(1_000_000, 260, 5 * 365),
                      IRSTrade(-400_000, 240, 2 * 365),
                      IRSTrade(250_000, 255, 10 * 365))
            portfolio = PortfolioState(
                party_a=a.identity, party_b=b.identity, oracle=o.identity,
                rate_ref=rate_ref, trades=trades)
            tx = TransactionBuilder(notary=notary.identity)
            tx.add_output_state(portfolio)
            tx.add_command(Command(ValueCommand(), (a.identity.owning_key,
                                                    b.identity.owning_key)))
            tx.sign_with(a.key)
            tx.sign_with(b.key)
            stx = tx.to_signed_transaction()
            a.record_transaction(stx)
            b.record_transaction(stx)

            handle = a.start_flow(SimmValuationFlow(stx.tx.out_ref(0).ref))
            net.run_network()
            final = handle.result.result()
            valued = [s.data for s in final.tx.outputs
                      if isinstance(s.data, PortfolioState)]
            expected = compute_valuation(trades, 2_5000)
            assert expected > 0  # a real margin, not a degenerate zero
            assert valued[0].valuation == expected
            # Both sides recorded the agreed valuation.
            for node in (a, b):
                assert node.services.storage_service.validated_transactions \
                    .get_transaction(final.id) is not None
        finally:
            net.stop_nodes()


def test_unilateral_valuation_rejected_at_contract_level():
    """Regression: a valuation command missing a participant's declared
    signature must fail contract verification."""
    from dataclasses import replace

    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.party import Party
    from corda_tpu.flows.oracle import FixOf
    from corda_tpu.testing.ledger_dsl import ledger
    from corda_tpu.tools.portfolio import PortfolioState, ValueCommand

    a = Party.of("A", KeyPair.generate(b"\x95" * 32).public)
    b = Party.of("B", KeyPair.generate(b"\x96" * 32).public)
    o = Party.of("O", KeyPair.generate(b"\x97" * 32).public)
    n = Party.of("N", KeyPair.generate(b"\x98" * 32).public)
    from corda_tpu.tools.simm import IRSTrade

    portfolio = PortfolioState(party_a=a, party_b=b, oracle=o,
                               rate_ref=FixOf("R", 1, "1D"),
                               trades=(IRSTrade(100_000, 250, 365),))

    l = ledger(n)
    with l.transaction() as tx:
        tx.input(portfolio)
        tx.output(replace(portfolio, valuation=1))
        tx.command(ValueCommand(), a.owning_key)  # B never signs
        tx.fails_with("both parties sign")
