"""Canonical codec: round-trips, determinism, whitelist enforcement.

Mirrors the reference's KryoTests coverage (reference:
core/src/test/kotlin/net/corda/core/serialization/KryoTests.kt) for the new
canonical format.
"""

from dataclasses import dataclass, field

import pytest

from corda_tpu.crypto import KeyPair, Party, SecureHash
from corda_tpu.serialization.codec import (
    DeserializationError,
    register,
    serialize,
    deserialize,
    serialized_hash,
)


@register
@dataclass(frozen=True)
class _Sample:
    name: str
    values: tuple = ()
    meta: dict = field(default_factory=dict)


class TestPrimitives:
    @pytest.mark.parametrize(
        "value",
        [
            None,
            True,
            False,
            0,
            1,
            -1,
            2**70,
            -(2**70),
            b"",
            b"\x00\xff" * 10,
            "",
            "unicode ✓ text",
            (),
            (1, "two", b"three", None),
            {"a": 1, "b": (2, 3)},
            frozenset({1, 2, 3}),
        ],
    )
    def test_roundtrip(self, value):
        assert deserialize(serialize(value).bytes) == value

    def test_lists_become_tuples(self):
        assert deserialize(serialize([1, 2, 3]).bytes) == (1, 2, 3)

    def test_dict_encoding_is_insertion_order_independent(self):
        assert serialize({"a": 1, "b": 2}).bytes == serialize({"b": 2, "a": 1}).bytes

    def test_frozenset_encoding_is_order_independent(self):
        a = frozenset({b"x", b"y", b"zzz"})
        b = frozenset([b"zzz", b"y", b"x"])
        assert serialize(a).bytes == serialize(b).bytes

    def test_trailing_garbage_rejected(self):
        blob = serialize(42).bytes + b"\x00"
        with pytest.raises(DeserializationError):
            deserialize(blob)

    def test_truncation_rejected(self):
        blob = serialize(b"payload-bytes").bytes
        with pytest.raises(DeserializationError):
            deserialize(blob[:-1])


class TestObjects:
    def test_dataclass_roundtrip(self):
        obj = _Sample("x", (1, 2), {"k": b"v"})
        assert deserialize(serialize(obj).bytes) == obj

    def test_unregistered_type_rejected_on_write(self):
        class Rogue:
            pass

        with pytest.raises(TypeError):
            serialize(Rogue())

    def test_unwhitelisted_name_rejected_on_read(self):
        obj = _Sample("x")
        blob = serialize(obj).bytes.replace(b"_Sample", b"_Evil00")
        with pytest.raises(DeserializationError):
            deserialize(blob)

    def test_determinism(self):
        kp = KeyPair.generate(b"\x07" * 32)
        party = Party.of("MegaCorp", kp.public)
        assert serialize(party).bytes == serialize(party).bytes
        assert serialized_hash(party) == serialized_hash(party)
        assert serialized_hash(party) != serialized_hash(Party.of("MiniCorp", kp.public))

    def test_nested_core_types(self):
        kp = KeyPair.generate(b"\x09" * 32)
        party = Party.of("MegaCorp", kp.public)
        sig = kp.sign(b"msg")
        value = {"party": party, "sig": sig, "hash": SecureHash.sha256(b"x")}
        assert deserialize(serialize(value).bytes) == value


# ---------------------------------------------------------------------------
# Decode-side canonicality (the codec rejects non-canonical byte strings)
# ---------------------------------------------------------------------------


def test_decoder_rejects_non_minimal_varint():
    import pytest
    from corda_tpu.serialization.codec import DeserializationError, deserialize

    # int 1 is tag 0x03 + zigzag(1)=2 -> varint [0x02]; [0x82, 0x00] encodes
    # the same value non-minimally.
    assert deserialize(bytes([0x03, 0x02])) == 1
    with pytest.raises(DeserializationError):
        deserialize(bytes([0x03, 0x82, 0x00]))


def test_decoder_rejects_unsorted_and_duplicate_dict_entries():
    import pytest
    from corda_tpu.serialization.codec import (
        DeserializationError, deserialize, serialize,
    )

    canonical = serialize({1: "a", 2: "b"}).bytes
    assert deserialize(canonical) == {1: "a", 2: "b"}
    # Swap the two entries: same decoded value, different bytes -> reject.
    body = canonical[2:]
    half = len(body) // 2
    swapped = canonical[:2] + body[half:] + body[:half]
    with pytest.raises(DeserializationError):
        deserialize(swapped)
    # Duplicate entry: entries compare equal -> reject (no silent collapse).
    dup = canonical[:2] + body[:half] + body[:half]
    with pytest.raises(DeserializationError):
        deserialize(dup)


def test_decoder_rejects_unsorted_frozenset():
    import pytest
    from corda_tpu.serialization.codec import (
        DeserializationError, deserialize, serialize,
    )

    canonical = serialize(frozenset([1, 2])).bytes
    assert deserialize(canonical) == frozenset([1, 2])
    body = canonical[2:]
    half = len(body) // 2
    swapped = canonical[:2] + body[half:] + body[:half]
    with pytest.raises(DeserializationError):
        deserialize(swapped)


def test_decoder_rejects_duplicate_key_with_differing_values():
    # Duplicate KEYS with ascending value encodings would pass a naive
    # (key, value)-pair ordering check; the decoder must compare keys alone.
    import pytest
    from corda_tpu.serialization.codec import DeserializationError, deserialize

    # dict {1:'a', 1:'b'}: tag 07, count 2, then (int 1,'a'), (int 1,'b')
    crafted = bytes.fromhex("070203020501610302050162")
    with pytest.raises(DeserializationError):
        deserialize(crafted)


def test_decoder_never_crashes_on_fuzzed_bytes():
    """Hostile-input property: arbitrary bytes either decode or raise
    DeserializationError — no other exception type, no hang (the codec is a
    wire surface; reference relies on controlled Kryo registration for the
    same guarantee)."""
    import random

    from corda_tpu.serialization.codec import (
        DeserializationError, deserialize, serialize,
    )

    rng = random.Random(1337)
    # Pure noise...
    for _ in range(300):
        blob = rng.randbytes(rng.randrange(0, 200))
        try:
            deserialize(blob)
        except DeserializationError:
            pass
    # ...and mutated VALID encodings (more likely to reach deep paths).
    from corda_tpu.crypto.hashes import SecureHash

    seed_values = [
        {"a": 1, "b": [1, 2, 3]},
        (SecureHash.zero(), "text", b"bytes", frozenset([1, 2])),
        [None, True, False, -12345678901234567890],
    ]
    for value in seed_values:
        good = bytearray(serialize(value).bytes)
        for _ in range(300):
            blob = bytearray(good)
            for _ in range(rng.randrange(1, 4)):
                pos = rng.randrange(len(blob))
                blob[pos] = rng.randrange(256)
            try:
                deserialize(bytes(blob))
            except DeserializationError:
                pass


def test_decoder_rejects_hostile_structures():
    """Regressions from fuzz review: deep nesting, bad token names, and
    failing custom decoders all surface as DeserializationError."""
    import pytest

    from corda_tpu.serialization.codec import (
        DeserializationError, deserialize,
    )

    # 5000-deep nested lists: bounded rejection, not RecursionError.
    with pytest.raises(DeserializationError, match="nesting too deep"):
        deserialize(b"\x06\x01" * 5000 + b"\x00")

    # Service token whose "name" is a dict: rejected inside a TokenContext.
    from corda_tpu.serialization.tokens import TokenContext

    blob = bytes([0x08, 13]) + b"__svc_token__" + bytes([0x01, 0x07, 0x00])
    with TokenContext():
        with pytest.raises(DeserializationError, match="must be a string"):
            deserialize(blob)


def test_decoder_rejects_unhashable_keys_and_members():
    import pytest
    from corda_tpu.serialization.codec import DeserializationError, deserialize

    with pytest.raises(DeserializationError, match="unhashable dict key"):
        deserialize(bytes([0x07, 0x01, 0x07, 0x00, 0x00]))  # dict key = dict
    with pytest.raises(DeserializationError, match="unhashable set member"):
        deserialize(bytes([0x09, 0x01, 0x07, 0x00]))  # set member = dict


class TestFloatCodec:
    """Float tag (0x0A): canonical 8-byte IEEE-754, finite only."""

    def test_roundtrip(self):
        for v in (0.0, 1.5, -2.25, 1e-300, 3.141592653589793, 180.4):
            assert deserialize(serialize(v).bytes) == v

    def test_negative_zero_normalized(self):
        assert serialize(-0.0).bytes == serialize(0.0).bytes

    def test_non_finite_rejected_on_encode(self):
        import math

        for v in (math.inf, -math.inf, math.nan):
            with pytest.raises(TypeError):
                serialize(v)

    def test_non_finite_rejected_on_decode(self):
        import struct

        for raw in (struct.pack(">d", 7.5)[:4],):  # truncated
            with pytest.raises(DeserializationError):
                deserialize(b"\x0a" + raw)
        inf_bits = struct.pack(">d", 1.0).replace(
            b"\x3f\xf0", b"\x7f\xf0", 1)
        with pytest.raises(DeserializationError):
            deserialize(b"\x0a" + inf_bits)
        neg_zero = (0x8000000000000000).to_bytes(8, "big")
        with pytest.raises(DeserializationError):
            deserialize(b"\x0a" + neg_zero)

    def test_distinct_from_int(self):
        assert deserialize(serialize(1.0).bytes) == 1.0
        assert isinstance(deserialize(serialize(1.0).bytes), float)
        assert isinstance(deserialize(serialize(1).bytes), int)
