"""Golden vectors for the batched SHA-256 kernel vs hashlib.

Reference semantics: SecureHash.sha256 content addressing (reference:
core/src/main/kotlin/net/corda/core/crypto/SecureHash.kt:33) and the Merkle
odd-node-duplicate rule (core/.../transactions/MerkleTransaction.kt:62-99).
"""

import hashlib
import random

import numpy as np

from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.merkle import MerkleTree
from corda_tpu.ops import sha256_jax as sj


def test_fixed_length_padding_edges():
    # Every padding regime: empty, <55, ==55 (one-block limit), 56-63
    # (length field spills to a second block), exact multiples of 64.
    rng = random.Random(7)
    for length in (0, 1, 31, 54, 55, 56, 57, 63, 64, 65, 119, 120, 128, 200):
        batch = np.array(
            [[rng.randrange(256) for _ in range(length)] for _ in range(5)],
            np.uint8).reshape(5, length)
        got = sj.sha256_fixed(batch)
        for i in range(5):
            assert got[i].tobytes() == hashlib.sha256(batch[i].tobytes()).digest(), length


def test_nist_vectors():
    # FIPS 180-2 examples.
    assert sj.sha256_many([b"abc"])[0] == bytes.fromhex(
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad")
    assert sj.sha256_many(
        [b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq"])[0] == bytes.fromhex(
        "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1")


def test_ragged_batch_buckets():
    rng = random.Random(11)
    msgs = [bytes(rng.randrange(256) for _ in range(n))
            for n in (0, 1, 3, 55, 56, 64, 57, 200, 1000, 64, 63, 119)]
    got = sj.sha256_many(msgs)
    assert [g for g in got] == [hashlib.sha256(m).digest() for m in msgs]


def test_merkle_root_matches_host_tree():
    for n in (1, 2, 3, 4, 5, 7, 8, 13, 16, 33):
        leaves = [SecureHash.sha256(bytes([i, n])) for i in range(n)]
        want = MerkleTree.build(leaves).hash.bytes
        got = sj.merkle_root_device([l.bytes for l in leaves])
        assert got == want, n


def test_pair_words_is_hash_concat():
    a = SecureHash.sha256(b"left")
    b = SecureHash.sha256(b"right")
    got = sj.merkle_root_device([a.bytes, b.bytes])
    assert got == a.hash_concat(b).bytes


def test_merkle_roots_device_batched_matches_host():
    # Same-leaf-count trees reduce together; mixed counts bucket. Must match
    # MerkleTree.build (odd-duplicate rule) bit-for-bit at every size.
    rng = random.Random(7)
    groups = []
    for n_leaves in (1, 2, 3, 4, 5, 7, 8, 9, 3, 8):
        groups.append([rng.randbytes(32) for _ in range(n_leaves)])
    got = sj.merkle_roots_device(groups)
    for g, leaves in zip(got, groups):
        want = MerkleTree.build([SecureHash(h) for h in leaves]).hash.bytes
        assert g == want


def test_hash_many_auto_backends_agree():
    msgs = [b"x" * n for n in range(0, 300, 7)]
    host, hb = sj.hash_many_auto(msgs, device_min=10**9)
    dev, db = sj.hash_many_auto(msgs, device_min=0)
    assert hb == "host" and db == "device"
    assert host == dev == [hashlib.sha256(m).digest() for m in msgs]


def test_prime_ids_seeds_caches_and_detects_tampering():
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.party import Party
    from corda_tpu.testing.dummies import DummyContract
    from corda_tpu.transactions.signed import SignedTransaction

    notary = Party.of("N", KeyPair.generate(b"\x51" * 32).public)
    party = Party.of("P", KeyPair.generate(b"\x52" * 32).public)
    stxs = []
    for i in range(6):
        b = DummyContract.generate_initial(party.ref(bytes([i + 1])), i, notary)
        b.sign_with(KeyPair.generate(b"\x52" * 32))
        stxs.append(b.to_signed_transaction(check_sufficient_signatures=False))

    # Strip caches by round-tripping through the codec.
    from corda_tpu.serialization.codec import deserialize, serialize
    fresh = [deserialize(serialize(s).bytes) for s in stxs]
    for backend_min in (10**9, 0):  # host path, then device path
        batch = [deserialize(serialize(s).bytes) for s in stxs]
        backend = SignedTransaction.prime_ids(batch, device_min=backend_min)
        assert backend == ("host" if backend_min else "device")
        for got, want in zip(batch, stxs):
            assert got.tx.id == want.tx.id  # cache hit, same id

    # A tampered payload must raise the same mismatch error .tx raises.
    import dataclasses
    victim = deserialize(serialize(stxs[0]).bytes)
    bad = dataclasses.replace(victim, id=stxs[1].id)
    try:
        SignedTransaction.prime_ids([bad])
        raise AssertionError("tampered id accepted")
    except ValueError as e:
        assert "does not match" in str(e)
