"""Golden vectors for the batched SHA-256 kernel vs hashlib.

Reference semantics: SecureHash.sha256 content addressing (reference:
core/src/main/kotlin/net/corda/core/crypto/SecureHash.kt:33) and the Merkle
odd-node-duplicate rule (core/.../transactions/MerkleTransaction.kt:62-99).
"""

import hashlib
import random

import numpy as np

from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.merkle import MerkleTree
from corda_tpu.ops import sha256_jax as sj


def test_fixed_length_padding_edges():
    # Every padding regime: empty, <55, ==55 (one-block limit), 56-63
    # (length field spills to a second block), exact multiples of 64.
    rng = random.Random(7)
    for length in (0, 1, 31, 54, 55, 56, 57, 63, 64, 65, 119, 120, 128, 200):
        batch = np.array(
            [[rng.randrange(256) for _ in range(length)] for _ in range(5)],
            np.uint8).reshape(5, length)
        got = sj.sha256_fixed(batch)
        for i in range(5):
            assert got[i].tobytes() == hashlib.sha256(batch[i].tobytes()).digest(), length


def test_nist_vectors():
    # FIPS 180-2 examples.
    assert sj.sha256_many([b"abc"])[0] == bytes.fromhex(
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad")
    assert sj.sha256_many(
        [b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq"])[0] == bytes.fromhex(
        "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1")


def test_ragged_batch_buckets():
    rng = random.Random(11)
    msgs = [bytes(rng.randrange(256) for _ in range(n))
            for n in (0, 1, 3, 55, 56, 64, 57, 200, 1000, 64, 63, 119)]
    got = sj.sha256_many(msgs)
    assert [g for g in got] == [hashlib.sha256(m).digest() for m in msgs]


def test_merkle_root_matches_host_tree():
    for n in (1, 2, 3, 4, 5, 7, 8, 13, 16, 33):
        leaves = [SecureHash.sha256(bytes([i, n])) for i in range(n)]
        want = MerkleTree.build(leaves).hash.bytes
        got = sj.merkle_root_device([l.bytes for l in leaves])
        assert got == want, n


def test_pair_words_is_hash_concat():
    a = SecureHash.sha256(b"left")
    b = SecureHash.sha256(b"right")
    got = sj.merkle_root_device([a.bytes, b.bytes])
    assert got == a.hash_concat(b).bytes
