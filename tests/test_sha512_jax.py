"""Golden vectors for the on-device SHA-512 challenge + sc_reduce kernel."""

import hashlib

import numpy as np
import pytest

from corda_tpu.ops import sha512_jax
from corda_tpu.ops.sha512_jax import L


def le_words(data: bytes) -> np.ndarray:
    """(N*32,) byte chunks -> (8, N) uint32 LE word array for one 32-byte
    value per column."""
    arr = np.frombuffer(data, np.uint8).reshape(-1, 32)
    return np.ascontiguousarray(arr).view("<u4").T.copy()


def make_inputs(n, seed=7):
    rng = np.random.default_rng(seed)
    r = rng.integers(0, 256, (n, 32), np.uint8).tobytes()
    a = rng.integers(0, 256, (n, 32), np.uint8).tobytes()
    m = rng.integers(0, 256, (n, 32), np.uint8).tobytes()
    return r, a, m


def test_sha512_96_matches_hashlib():
    n = 17
    r, a, m = make_inputs(n)
    hi, lo = sha512_jax.sha512_96_words(le_words(r), le_words(a), le_words(m))
    hi, lo = np.asarray(hi), np.asarray(lo)
    for i in range(n):
        want = hashlib.sha512(
            r[32 * i:32 * i + 32] + a[32 * i:32 * i + 32]
            + m[32 * i:32 * i + 32]).digest()
        got = b"".join(
            int(hi[w, i]).to_bytes(4, "big") + int(lo[w, i]).to_bytes(4, "big")
            for w in range(8))
        assert got == want, f"digest {i} diverged"


def _reduce_via_kernel(digests: list[bytes]) -> list[int]:
    hi = np.zeros((8, len(digests)), np.uint32)
    lo = np.zeros((8, len(digests)), np.uint32)
    for i, d in enumerate(digests):
        for w in range(8):
            word = int.from_bytes(d[8 * w:8 * w + 8], "big")
            hi[w, i] = word >> 32
            lo[w, i] = word & 0xFFFFFFFF
    words = np.asarray(sha512_jax.sc_reduce_words(hi, lo))
    out = []
    for i in range(len(digests)):
        out.append(sum(int(words[w, i]) << (32 * w) for w in range(8)))
    return out


def test_sc_reduce_random():
    rng = np.random.default_rng(11)
    digests = [rng.integers(0, 256, 64, np.uint8).tobytes() for _ in range(64)]
    got = _reduce_via_kernel(digests)
    for d, g in zip(digests, got):
        assert g == int.from_bytes(d, "little") % L


def test_sc_reduce_edge_values():
    edges = [0, 1, L - 1, L, L + 1, 2 * L, 3 * L - 1, 2**252, 2**252 - 1,
             2**255 - 19, 2**256 - 1, 2**511, 2**512 - 1,
             (2**512 - 1) // L * L,  # largest multiple of L
             L * (2**259) + L - 1]
    digests = [e.to_bytes(64, "little") for e in edges]
    got = _reduce_via_kernel(digests)
    for e, g in zip(edges, got):
        assert g == e % L, f"edge {e:#x}: got {g:#x}"


def test_challenge_words_end_to_end():
    n = 9
    r, a, m = make_inputs(n, seed=23)
    words = np.asarray(sha512_jax.challenge_words(
        le_words(r), le_words(a), le_words(m)))
    for i in range(n):
        digest = hashlib.sha512(
            r[32 * i:32 * i + 32] + a[32 * i:32 * i + 32]
            + m[32 * i:32 * i + 32]).digest()
        want = int.from_bytes(digest, "little") % L
        got = sum(int(words[w, i]) << (32 * w) for w in range(8))
        assert got == want, f"challenge {i} diverged"
