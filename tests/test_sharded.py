"""Multi-chip SPMD verify on the virtual 8-device CPU mesh.

The mesh is the only difference from the single-chip path; accept/reject must
stay bit-identical to the CPU oracle (SURVEY.md §7 hard part #5).  The driver
additionally exercises __graft_entry__.dryrun_multichip out-of-process.
"""

import numpy as np
import pytest

import jax

from corda_tpu.crypto import ref_ed25519 as ref
from corda_tpu.ops import sharded


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def _sig_fixture(n):
    pks, msgs, sigs = [], [], []
    for i in range(n):
        sk = bytes([(i % 255) + 1]) * 32
        pk = ref.public_key(sk)
        m = b"shard-%d" % i
        s = ref.sign(sk, m)
        if i % 3 == 2:  # corrupt a third: R byte, S byte, or pubkey
            which = i % 9
            if which == 2:
                s = bytes([s[0] ^ 1]) + s[1:]
            elif which == 5:
                s = s[:40] + bytes([s[40] ^ 1]) + s[41:]
            else:
                pk = bytes([pk[0] ^ 1]) + pk[1:]
        pks.append(pk)
        msgs.append(m)
        sigs.append(s)
    return pks, msgs, sigs


def test_sharded_verify_matches_oracle():
    mesh = sharded.make_mesh(8)
    pks, msgs, sigs = _sig_fixture(19)  # ragged: exercises pad-and-mask
    got = sharded.verify_batch_sharded(pks, msgs, sigs, mesh)
    want = np.array([ref.verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)])
    assert got.tolist() == want.tolist()
    assert want.sum() not in (0, len(want))  # fixture mixes accept and reject


def test_sharded_rejects_malformed_without_raising():
    mesh = sharded.make_mesh(8)
    pks, msgs, sigs = _sig_fixture(4)
    pks[1] = b"\x01" * 7        # wrong-length key
    sigs[2] = b"\x02" * 11      # wrong-length sig
    got = sharded.verify_batch_sharded(pks, msgs, sigs, mesh)
    assert got[0] and not got[1] and not got[2]


def test_pad_to_devices():
    assert sharded.pad_to_devices(1, 8) == 8
    assert sharded.pad_to_devices(8, 8) == 8
    assert sharded.pad_to_devices(9, 8) == 16
    assert sharded.pad_to_devices(0, 8) == 8


def test_sharded_device_hash_path_matches_oracle():
    """32-byte messages route the fully-on-device graph (SHA-512 challenge +
    mod-L + verify) through the same mesh; accept set must match the
    oracle, and must match the host-hash sharded path bit-for-bit."""
    mesh = sharded.make_mesh(8)
    pks, msgs, sigs = _sig_fixture(19)
    msgs32 = [m.ljust(32, b".") for m in msgs]
    sigs32 = [ref.sign(bytes([(i % 255) + 1]) * 32, msgs32[i])
              if ref.verify(pks[i], msgs[i], sigs[i]) else sigs[i]
              for i in range(len(sigs))]
    got = sharded.verify_batch_sharded(pks, msgs32, sigs32, mesh)
    want = [ref.verify(p, m, s) for p, m, s in zip(pks, msgs32, sigs32)]
    assert got.tolist() == want
    assert any(want) and not all(want)


def test_mesh_verifier_provider_on_mesh():
    # The PRODUCT seam (round-3 VERDICT item 4): MeshVerifier drives the
    # sharded tier through the same BatchVerifier interface every framework
    # call site uses, selectable as verifier = "jax-sharded" in NodeConfig.
    from corda_tpu.crypto.provider import MeshVerifier, VerifyJob, make_verifier

    v = make_verifier("jax-sharded")
    assert isinstance(v, MeshVerifier) and v.name == "jax-sharded"
    # device_min_sigs=0 pins the mesh route (the size crossover would
    # send 21 jobs to the host tier and test nothing sharded).
    v = MeshVerifier(n_devices=8, device_min_sigs=0)
    pks, msgs, sigs = _sig_fixture(21)
    jobs = [VerifyJob(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    got = v.verify_batch(jobs)
    want = [ref.verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert got.tolist() == want
    assert v.mesh.devices.size == 8
    assert (v.device_batches, v.host_batches) == (1, 0)
    assert v.verify_batch([]).tolist() == []


def test_mesh_verifier_shadow_divergence_raises():
    from corda_tpu.crypto.provider import MeshVerifier, VerifyJob

    v = MeshVerifier(n_devices=8, shadow_rate=1.0, device_min_sigs=0)
    pks, msgs, sigs = _sig_fixture(5)
    jobs = [VerifyJob(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    got = v.verify_batch(jobs)  # agreement: no raise
    assert len(got) == 5


def test_node_config_selects_mesh_verifier(tmp_path):
    # A node flips multi-chip verification on with ONE config line.
    from corda_tpu.node.config import NodeConfig
    from corda_tpu.node.node import Node

    cfg = tmp_path / "node.toml"
    cfg.write_text(
        f'name = "Meshy"\nbase_dir = "{tmp_path}/meshy"\n'
        f'verifier = "jax-sharded"\n')
    node = Node(NodeConfig.load(str(cfg))).start()
    try:
        assert node.smm.verifier.name == "jax-sharded"
        assert node.smm.verifier.mesh.devices.size == len(jax.devices())
    finally:
        node.stop()
