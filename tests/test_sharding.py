"""Sharded notary: shard map, deterministic reservation TTL, cross-shard 2PC.

Three tiers:

* pure functions (shard_of / service strings / config parsing) — no I/O;
* the replicated state machine's reservation semantics, driven through
  make_apply_command directly against a NodeDatabase with HAND-CRAFTED
  issued_at stamps (determinism means expiry is arithmetic, so the tests
  need no sleeps and no clocks);
* real in-process Nodes — two single-member raft groups over TCP + sqlite —
  driving ShardedUniquenessProvider's poll machines end to end: fast path,
  remote forwarding, the two-phase commit, the cross-shard double-spend
  race, and TTL release after a simulated coordinator crash.

The multi-process soaks (chaos plan + leader kill, driver shard cluster)
are @slow — they boot whole process fleets and stay out of tier-1.
"""

import time

import pytest

from corda_tpu.contracts.structures import StateRef
from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.node.config import NodeConfig, ShardConfig
from corda_tpu.node.node import Node
from corda_tpu.node.services.api import UniquenessConflict, UniquenessException
from corda_tpu.node.services.persistence import NodeDatabase
from corda_tpu.node.services.raft import (
    BUSY,
    WRONG_EPOCH,
    AbortReservedCommand,
    CommitReservedCommand,
    InstallShardStateCommand,
    PutAllCommand,
    ReserveCommand,
    ShardFenceCommand,
    WrongShardEpochException,
    make_apply_command,
)
from corda_tpu.node.services.sharding import (
    ShardedUniquenessProvider,
    parse_reshard_plan,
    parse_shard_service,
    parse_shard_service_full,
    publish_reshard_plan,
    reshard_plan_string,
    shard_of,
    shard_service_string,
    split_by_shard,
)
from corda_tpu.serialization.codec import deserialize, serialize


def _ref(tag: str, index: int = 0) -> StateRef:
    return StateRef(SecureHash.sha256(tag.encode()), index)


def _ref_in_group(group: int, count: int = 2, salt: str = "") -> StateRef:
    i = 0
    while True:
        ref = _ref(f"state-{salt}-{i}")
        if shard_of(ref, count) == group:
            return ref
        i += 1


# -- shard map ---------------------------------------------------------------


def test_shard_of_is_deterministic_and_spreads():
    refs = [_ref(f"s{i}") for i in range(400)]
    for count in (2, 3, 4):
        owners = [shard_of(r, count) for r in refs]
        assert owners == [shard_of(r, count) for r in refs]  # pure
        per_group = [owners.count(g) for g in range(count)]
        assert all(n > 0 for n in per_group), per_group
        # A SHA-256-derived keyspace should not skew grossly.
        assert max(per_group) < 2.5 * min(per_group), per_group
    # count <= 1 is always group 0 (the unsharded degenerate case).
    assert all(shard_of(r, 1) == 0 for r in refs[:10])
    assert all(shard_of(r, 0) == 0 for r in refs[:10])


def test_shard_of_spreads_outputs_of_one_transaction():
    # The XOR with the output index exists so one transaction's outputs do
    # not all land on the shard its txhash happens to pick.
    h = SecureHash.sha256(b"one-tx")
    owners = {shard_of(StateRef(h, i), 4) for i in range(8)}
    assert len(owners) > 1


def test_split_by_shard_partitions_and_preserves_order():
    refs = [_ref(f"p{i}") for i in range(40)]
    by_group = split_by_shard(refs, 4)
    assert {r for g in by_group.values() for r in g} == set(refs)
    for g, grefs in by_group.items():
        assert all(shard_of(r, 4) == g for r in grefs)
        # Order preserved WITHIN a group (commit/abort replay the same
        # ref order the reserve claimed).
        assert sorted(grefs, key=refs.index) == list(grefs)


def test_shard_service_string_roundtrip_and_rejects():
    assert parse_shard_service(shard_service_string(2, 4)) == (2, 4)
    assert parse_shard_service(shard_service_string(0, 1)) == (0, 1)
    for bad in ("corda.notary.simple",          # not the shard prefix
                "corda.notary.shard.4of4",      # group out of range
                "corda.notary.shard.-1of4",
                "corda.notary.shard.1of0",
                "corda.notary.shard.xof4",
                "corda.notary.shard.2of",
                "corda.notary.shard."):
        assert parse_shard_service(bad) is None, bad


def test_config_parses_and_validates_notary_shards(tmp_path):
    raw = {"name": "ShardA", "notary": "raft-simple",
           "raft_cluster": ["ShardA"],
           "notary_shards": {"groups": [["ShardA"], ["ShardB"]],
                             "reserve_ttl_s": 3.5}}
    cfg = NodeConfig.from_dict(dict(raw), default_dir=tmp_path)
    assert cfg.notary_shards == ShardConfig(
        count=2, groups=(("ShardA",), ("ShardB",)), reserve_ttl_s=3.5)

    with pytest.raises(ValueError, match="count=3 but 2 groups"):
        NodeConfig.from_dict(
            {**raw, "notary_shards": {"count": 3,
                                      "groups": [["A"], ["B"]]}},
            default_dir=tmp_path)
    with pytest.raises(ValueError, match="requires a raft"):
        NodeConfig.from_dict(
            {"name": "N", "notary": "simple",
             "notary_shards": {"groups": [["N"]]}}, default_dir=tmp_path)


def test_netmap_register_is_race_free_under_concurrent_boots(tmp_path):
    """Members of a sharded topology boot in parallel and all register in
    the SAME netmap file. The load-modify-replace must be serialised
    (flock): before it was, two simultaneous registrations could each read
    the map missing the other and the loser's entry was silently dropped —
    that group's member stayed unreachable for the whole run (observed as
    per_group_committed [n, 0] with every group-1 tx timing out)."""
    import threading

    from corda_tpu.node.config import netmap_load, netmap_register

    path = tmp_path / "netmap.json"
    names = [f"Node{i}" for i in range(8)]
    keys = {n: KeyPair.generate().public.composite for n in names}
    barrier = threading.Barrier(len(names))

    def boot(name):
        barrier.wait()
        for round_ in range(6):  # re-register like a self-heal would
            netmap_register(path, name, "127.0.0.1", 10_000,
                            keys[name], (f"svc.{name}.{round_}",))

    threads = [threading.Thread(target=boot, args=(n,)) for n in names]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    entries = {e.name: e for e in netmap_load(path)}
    assert sorted(entries) == names  # nobody's registration was clobbered
    # Same-name re-registration replaced, not duplicated, and kept the
    # LAST round's services.
    assert all(entries[n].services == (f"svc.{n}.5",) for n in names)


# -- replicated reservation semantics (no clocks, no sleeps) -----------------


CALLER = Party.of("Tester", KeyPair.generate().public)
TX_A = SecureHash.sha256(b"tx-a")
TX_B = SecureHash.sha256(b"tx-b")
T0 = 1000.0  # an arbitrary coordinator stamp: expiry is pure arithmetic


def _mk(tmp_path):
    db = NodeDatabase(tmp_path / "apply.sqlite")
    return make_apply_command(db), db


def _reserved(db):
    return db.conn.execute(
        "SELECT COUNT(*) FROM reserved_states").fetchone()[0]


def _committed(db):
    return db.conn.execute(
        "SELECT COUNT(*) FROM committed_states").fetchone()[0]


def test_reserve_blocks_unexpired_then_deterministically_steals(tmp_path):
    apply, db = _mk(tmp_path)
    r1 = _ref("ttl-1")
    assert apply(ReserveCommand((r1,), TX_A, CALLER, b"r1",
                                issued_at=T0, ttl_s=5.0)) is None
    assert _reserved(db) == 1
    # A different tx stamped INSIDE the hold bounces (retryable).
    assert apply(ReserveCommand((r1,), TX_B, CALLER, b"r2",
                                issued_at=T0 + 4.9, ttl_s=5.0)) is BUSY
    # The same tx refreshes its own hold (retried phase 1): expiry moves.
    assert apply(ReserveCommand((r1,), TX_A, CALLER, b"r3",
                                issued_at=T0 + 1.0, ttl_s=5.0)) is None
    assert apply(ReserveCommand((r1,), TX_B, CALLER, b"r4",
                                issued_at=T0 + 5.5, ttl_s=5.0)) is BUSY
    # Stamped AT/PAST the refreshed expiry: the deterministic steal — the
    # crashed-coordinator release needs no clock and no janitor.
    assert apply(ReserveCommand((r1,), TX_B, CALLER, b"r5",
                                issued_at=T0 + 6.0, ttl_s=5.0)) is None
    assert _reserved(db) == 1  # REPLACEd, not accumulated


def test_reserve_is_atomic_per_group(tmp_path):
    apply, db = _mk(tmp_path)
    r1, r2 = _ref("atomic-1"), _ref("atomic-2")
    assert apply(ReserveCommand((r2,), TX_B, CALLER, b"r1",
                                issued_at=T0, ttl_s=50.0)) is None
    # TX_A wants both; r2 is held -> BUSY and r1 must NOT be taken (a
    # partial hold would be a lock leak the coordinator never learns of).
    assert apply(ReserveCommand((r1, r2), TX_A, CALLER, b"r2",
                                issued_at=T0 + 1, ttl_s=50.0)) is BUSY
    assert _reserved(db) == 1


def test_putall_respects_and_clears_reservations(tmp_path):
    apply, db = _mk(tmp_path)
    r1 = _ref("put-1")
    assert apply(ReserveCommand((r1,), TX_A, CALLER, b"r1",
                                issued_at=T0, ttl_s=5.0)) is None
    # Foreign unexpired hold bounces a plain commit too (the single-shard
    # fast path must not race a 2PC mid-flight).
    assert apply(PutAllCommand((r1,), TX_B, CALLER, b"p1",
                               issued_at=T0 + 1)) is BUSY
    # The holder itself commits straight through and the hold dissolves.
    assert apply(PutAllCommand((r1,), TX_A, CALLER, b"p2",
                               issued_at=T0 + 1)) is None
    assert (_reserved(db), _committed(db)) == (0, 1)
    # Now the spend is FINAL for everyone else, however late the stamp.
    out = apply(PutAllCommand((r1,), TX_B, CALLER, b"p3",
                              issued_at=T0 + 9999))
    assert isinstance(out, UniquenessConflict)
    # ... and idempotent for the committing tx (re-applied log entries).
    assert apply(PutAllCommand((r1,), TX_A, CALLER, b"p4",
                               issued_at=T0 + 9999)) is None
    assert _committed(db) == 1


def test_commit_reserved_idempotent_and_never_blocked_by_holds(tmp_path):
    apply, db = _mk(tmp_path)
    r1, r2 = _ref("cr-1"), _ref("cr-2")
    assert apply(ReserveCommand((r1,), TX_A, CALLER, b"r1",
                                issued_at=T0, ttl_s=5.0)) is None
    assert apply(CommitReservedCommand((r1,), TX_A, CALLER, b"c1")) is None
    assert (_reserved(db), _committed(db)) == (0, 1)
    # Idempotent: a coordinator retry of phase 2 converges.
    assert apply(CommitReservedCommand((r1,), TX_A, CALLER, b"c2")) is None
    assert _committed(db) == 1
    # Phase-2 TERMINATION: a foreign (even unexpired) hold does not block
    # the commit — the reservation was won in phase 1; re-checking here
    # would let a TTL steal wedge a half-committed 2PC forever. The
    # resulting steal window is the documented tradeoff.
    assert apply(ReserveCommand((r2,), TX_B, CALLER, b"r2",
                                issued_at=T0, ttl_s=10_000.0)) is None
    assert apply(CommitReservedCommand((r2,), TX_A, CALLER, b"c3")) is None
    assert _committed(db) == 2
    # Committed-by-another-tx stays final though.
    out = apply(CommitReservedCommand((r1,), TX_B, CALLER, b"c4"))
    assert isinstance(out, UniquenessConflict)


def test_abort_releases_only_its_own_holds(tmp_path):
    apply, db = _mk(tmp_path)
    r1, r2 = _ref("ab-1"), _ref("ab-2")
    assert apply(ReserveCommand((r1,), TX_A, CALLER, b"r1",
                                issued_at=T0, ttl_s=50.0)) is None
    assert apply(ReserveCommand((r2,), TX_B, CALLER, b"r2",
                                issued_at=T0, ttl_s=50.0)) is None
    # TX_A aborts both refs; only ITS hold may dissolve (a late abort from
    # a retried coordinator must not release someone else's phase 1).
    assert apply(AbortReservedCommand((r1, r2), TX_A, b"a1")) is None
    assert _reserved(db) == 1
    row = db.conn.execute(
        "SELECT tx_id FROM reserved_states").fetchone()
    assert bytes(row[0]) == TX_B.bytes
    # Aborting nothing is fine — abort never adds a failure mode.
    assert apply(AbortReservedCommand((r1,), TX_A, b"a2")) is None


# -- in-process cross-shard networks -----------------------------------------


SHARD_NAMES = ("ShardA", "ShardB")


def make_shard_net(tmp_path, ttl_s=15.0):
    cfg = ShardConfig(count=2, groups=(("ShardA",), ("ShardB",)),
                      reserve_ttl_s=ttl_s)
    nodes = []
    for name in SHARD_NAMES:
        nodes.append(Node(NodeConfig(
            name=name,
            base_dir=tmp_path / name,
            notary="raft-simple",
            raft_cluster=(name,),
            network_map=tmp_path / "netmap.json",
            notary_shards=cfg,
        )).start())
    for n in nodes:
        n.refresh_netmap()
    return nodes


def wait_group_leaders(nodes, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for n in nodes:
            n.run_once(timeout=0.005)
        if all(n.raft_member.role == "leader" for n in nodes):
            for n in nodes:
                n.refresh_netmap()
            return
    raise AssertionError("single-member groups failed to self-elect")


def drive(nodes, poll, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = poll()
        if out is not None:
            return out
        for n in nodes:
            n.run_once(timeout=0.005)
            n.refresh_netmap_maybe(every=0.2)
    raise AssertionError("poll did not decide in time")


def test_node_boots_sharded_provider_and_advertises_group(tmp_path):
    nodes = make_shard_net(tmp_path)
    try:
        for i, n in enumerate(nodes):
            assert isinstance(n.uniqueness_provider,
                              ShardedUniquenessProvider)
            assert n.uniqueness_provider.my_group == i
        # The shard service string rides the netmap so CLIENTS can build
        # the directory from the map alone.
        from corda_tpu.flows.notary import _shard_directory

        class _FakeFlow:
            class service_hub:
                network_map_cache = nodes[0].services.network_map_cache

        directory = _shard_directory(_FakeFlow)
        assert directory is not None
        count, groups = directory
        assert count == 2
        assert sorted(p.name for ps in groups.values() for p in ps) == \
            list(SHARD_NAMES)
    finally:
        for n in nodes:
            n.stop()


def test_single_shard_fast_path_and_remote_forwarding(tmp_path):
    nodes = make_shard_net(tmp_path)
    try:
        wait_group_leaders(nodes)
        prov = nodes[0].uniqueness_provider
        # Fast path: a ref OWNED by the local group — plain raft commit.
        local_ref = _ref_in_group(0, salt="fast")
        assert drive(nodes, prov.commit_async(
            (local_ref,), SecureHash.sha256(b"fast-tx"),
            nodes[0].identity)) is True
        assert prov.stamp()["single_shard"] == 1
        assert nodes[0].uniqueness_provider.committed_count == 1
        # Remote single group: committed THROUGH node 0, lands on group 1's
        # ledger — no 2PC, one forwarded PutAll.
        remote_ref = _ref_in_group(1, salt="remote")
        assert drive(nodes, prov.commit_async(
            (remote_ref,), SecureHash.sha256(b"remote-tx"),
            nodes[0].identity)) is True
        assert prov.stamp()["remote_single"] == 1
        assert nodes[1].uniqueness_provider.committed_count == 1
        assert nodes[0].uniqueness_provider.committed_count == 1
    finally:
        for n in nodes:
            n.stop()


def test_cross_shard_two_phase_commit_and_double_spend(tmp_path):
    nodes = make_shard_net(tmp_path)
    try:
        wait_group_leaders(nodes)
        prov = nodes[0].uniqueness_provider
        ra = _ref_in_group(0, salt="x0")
        rb = _ref_in_group(1, salt="x1")
        tx1 = SecureHash.sha256(b"cross-tx-1")
        assert drive(nodes, prov.commit_async(
            (ra, rb), tx1, nodes[0].identity)) is True
        assert prov.stamp()["cross_shard"] == 1
        # Each group durably owns its half; no reservation survives.
        for n in nodes:
            assert n.uniqueness_provider.committed_count == 1
            assert n.raft_member.db.conn.execute(
                "SELECT COUNT(*) FROM reserved_states").fetchone()[0] == 0
        # Exactly-once: a retry of the SAME tx converges to success
        # (reserve treats committed-by-this-tx as ok; commit idempotent).
        assert drive(nodes, prov.commit_async(
            (ra, rb), tx1, nodes[0].identity)) is True
        for n in nodes:
            assert n.uniqueness_provider.committed_count == 1
        # A DIFFERENT tx spending either half is a final double-spend.
        poll = prov.commit_async((ra,), SecureHash.sha256(b"thief"),
                                 nodes[0].identity)
        with pytest.raises(UniquenessException):
            drive(nodes, poll)
    finally:
        for n in nodes:
            n.stop()


def test_concurrent_cross_shard_race_exactly_one_wins(tmp_path):
    """Two coordinators (one per group) race the SAME two inputs with
    different txs. Ordered acquisition serializes them at the lowest
    contended group: exactly one commits, the other sees a final conflict,
    and the ledgers hold each ref exactly once."""
    nodes = make_shard_net(tmp_path, ttl_s=60.0)  # TTL must NOT be the
    # resolution mechanism here — a steal would mask an ordering bug
    try:
        wait_group_leaders(nodes)
        ra = _ref_in_group(0, salt="race0")
        rb = _ref_in_group(1, salt="race1")
        polls = {
            "a": nodes[0].uniqueness_provider.commit_async(
                (ra, rb), SecureHash.sha256(b"race-a"), nodes[0].identity),
            "b": nodes[1].uniqueness_provider.commit_async(
                (ra, rb), SecureHash.sha256(b"race-b"), nodes[1].identity),
        }
        outcomes = {}
        deadline = time.monotonic() + 30.0
        while len(outcomes) < 2 and time.monotonic() < deadline:
            for key, poll in polls.items():
                if key in outcomes:
                    continue
                try:
                    out = poll()
                except UniquenessException:
                    outcomes[key] = "conflict"
                else:
                    if out is not None:
                        outcomes[key] = "ok"
            for n in nodes:
                n.run_once(timeout=0.005)
                n.refresh_netmap_maybe(every=0.2)
        assert sorted(outcomes.values()) == ["conflict", "ok"], outcomes
        # Each ref committed exactly once across the two ledgers, and the
        # loser's unwind left no live reservation anywhere.
        for n in nodes:
            assert n.uniqueness_provider.committed_count == 1
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            leaks = sum(n.raft_member.db.conn.execute(
                "SELECT COUNT(*) FROM reserved_states").fetchone()[0]
                for n in nodes)
            if leaks == 0:
                break
            for n in nodes:  # the loser's aborts are still in flight
                n.run_once(timeout=0.005)
        assert leaks == 0
    finally:
        for n in nodes:
            n.stop()


def test_crashed_coordinator_reservation_released_by_ttl(tmp_path):
    """A reservation whose coordinator vanished (simulated: the command is
    injected directly, no 2PC follows) must release by TTL: a later spend
    bounces while the hold is live, then steals deterministically once its
    re-stamped resubmission passes the expiry."""
    nodes = make_shard_net(tmp_path, ttl_s=1.0)
    try:
        wait_group_leaders(nodes)
        victim_ref = _ref_in_group(1, salt="crash")
        ghost_tx = SecureHash.sha256(b"ghost-coordinator")
        import os as _os
        nodes[1].raft_member.submit(ReserveCommand(
            (victim_ref,), ghost_tx, nodes[1].identity, _os.urandom(16),
            issued_at=time.time(), ttl_s=1.0))

        def _held():
            return nodes[1].raft_member.db.conn.execute(
                "SELECT COUNT(*) FROM reserved_states").fetchone()[0]

        deadline = time.monotonic() + 10.0
        while _held() == 0 and time.monotonic() < deadline:
            for n in nodes:
                n.run_once(timeout=0.005)
        assert _held() == 1  # the ghost's hold is replicated and live

        # Now a real client spends through node 0 (remote single-group
        # path): resubmissions re-stamp issued_at every 0.5 s, so the poll
        # bounces BUSY until the stamp passes expiry, then commits.
        prov = nodes[0].uniqueness_provider
        t0 = time.monotonic()
        assert drive(nodes, prov.commit_async(
            (victim_ref,), SecureHash.sha256(b"claimant"),
            nodes[0].identity), timeout=20.0) is True
        assert time.monotonic() - t0 >= 0.5  # it actually waited the hold out
        assert _held() == 0
        assert nodes[1].uniqueness_provider.committed_count == 1
    finally:
        for n in nodes:
            n.stop()


# -- elastic resharding (round 13) -------------------------------------------


def test_epoch_service_strings_and_reshard_plan_parse():
    # Epoch 0 emits the BARE pre-reshard format (old clients keep parsing).
    assert shard_service_string(2, 4) == "corda.notary.shard.2of4"
    assert parse_shard_service_full(shard_service_string(2, 4)) == (2, 4, 0)
    assert parse_shard_service_full(shard_service_string(2, 4, epoch=3)) \
        == (2, 4, 3)
    # The 2-tuple parser stays epoch-blind for its existing callers.
    assert parse_shard_service(shard_service_string(2, 4, epoch=3)) == (2, 4)
    assert parse_shard_service_full("corda.notary.shard.2of4@x") is None
    assert parse_reshard_plan(reshard_plan_string(1, 2, 4)) == (1, 2, 4)
    assert parse_reshard_plan(reshard_plan_string(2, 4, 2)) == (2, 4, 2)
    for bad in ("corda.notary.reshard.0:2to4",   # epoch must be >= 1
                "corda.notary.reshard.1:2to3",   # not a double/halve
                "corda.notary.reshard.1:2to",
                "corda.notary.shard.1of2"):
        assert parse_reshard_plan(bad) is None, bad


def test_seal_fences_only_the_moving_keyspace(tmp_path):
    """mode="seal" on the source of a 1 -> 2 split: refs moving to the new
    group bounce WRONG_EPOCH (retryable after a directory re-derive), refs
    the group keeps commit straight through — the unmoved majority sees no
    outage. Abort stays exempt so 2PC unwinds never wedge on a fence."""
    apply, db = _mk(tmp_path)
    kept = _ref_in_group(0, count=2, salt="seal-keep")
    moved = _ref_in_group(1, count=2, salt="seal-move")
    assert apply(PutAllCommand((moved,), TX_A, CALLER, b"p0",
                               issued_at=T0)) is None
    assert apply(ShardFenceCommand(0, 1, 2, 1, "seal", b"f1")) is None
    moved2 = _ref_in_group(1, count=2, salt="seal-move-2")
    assert apply(PutAllCommand((moved2,), TX_B, CALLER, b"p1",
                               issued_at=T0 + 1)) is WRONG_EPOCH
    assert apply(ReserveCommand((moved2,), TX_B, CALLER, b"r1",
                                issued_at=T0 + 1, ttl_s=5.0)) is WRONG_EPOCH
    assert apply(PutAllCommand((kept,), TX_B, CALLER, b"p2",
                               issued_at=T0 + 1)) is None
    # Abort is NEVER fenced: releasing holds must work mid-handoff.
    assert apply(AbortReservedCommand((moved2,), TX_B, b"a1")) is None
    # Seal is idempotent (coordinator retry / log replay).
    assert apply(ShardFenceCommand(0, 1, 2, 1, "seal", b"f2")) is None


def test_handoff_install_activate_and_purge(tmp_path):
    """The full two-phase state handoff at the apply layer: seal the
    source, stream the moved slice, fence-then-activate the target, purge
    the source. Exactly-once is structural — the moved spend stays final
    on the new owner (with its consuming-tx provenance), and the sum of
    per-group rows never double-counts."""
    for d in ("src", "dst"):
        (tmp_path / d).mkdir()
    s_apply, s_db = _mk(tmp_path / "src")
    t_apply, t_db = _mk(tmp_path / "dst")
    kept = _ref_in_group(0, count=2, salt="ho-keep")
    moved = _ref_in_group(1, count=2, salt="ho-move")
    assert s_apply(PutAllCommand((kept, moved), TX_A, CALLER, b"p0",
                                 issued_at=T0)) is None
    assert s_apply(ShardFenceCommand(0, 1, 2, 1, "seal", b"f0")) is None
    rows = s_db.conn.execute(
        "SELECT state_ref, consuming FROM committed_states").fetchall()
    moved_rows = tuple(
        (bytes(b), bytes(c)) for b, c in rows
        if shard_of(deserialize(bytes(b)), 2) == 1)
    assert len(moved_rows) == 1
    assert t_apply(InstallShardStateCommand(
        moved_rows, (), 1, 1, 2, 1, b"i0")) is None
    # First frame fenced the target "importing": a new-epoch client racing
    # ahead of the cutover bounces instead of committing against a
    # half-installed ledger.
    assert t_apply(PutAllCommand((moved,), TX_B, CALLER, b"p1",
                                 issued_at=T0 + 1)) is WRONG_EPOCH
    # Re-install is idempotent (retried frame / log replay).
    assert t_apply(InstallShardStateCommand(
        moved_rows, (), 1, 1, 2, 1, b"i1")) is None
    assert _committed(t_db) == 1
    assert t_apply(ShardFenceCommand(1, 1, 2, 1, "activate", b"f1")) is None
    # Final for a thief — the streamed row carries its consuming tx...
    out = t_apply(PutAllCommand((moved,), TX_B, CALLER, b"p2",
                                issued_at=T0 + 2))
    assert isinstance(out, UniquenessConflict)
    # ...and idempotent for the committing tx (retries converge).
    assert t_apply(PutAllCommand((moved,), TX_A, CALLER, b"p3",
                                 issued_at=T0 + 2)) is None
    # The target only serves the keyspace it owns at the new count.
    assert t_apply(PutAllCommand((kept,), TX_B, CALLER, b"p4",
                                 issued_at=T0 + 2)) is WRONG_EPOCH
    # Source activation purges the moved rows (the target's quorum owns
    # them durably by now) and keeps the rest — the cross-group row sum
    # stays exactly the consumed refs.
    assert s_apply(ShardFenceCommand(0, 1, 2, 1, "activate", b"f2")) is None
    assert _committed(s_db) == 1
    (left,) = s_db.conn.execute(
        "SELECT state_ref FROM committed_states").fetchone()
    assert shard_of(deserialize(bytes(left)), 2) == 0
    assert s_apply(PutAllCommand((moved,), TX_B, CALLER, b"p5",
                                 issued_at=T0 + 3)) is WRONG_EPOCH


def test_streamed_reservation_releases_by_original_ttl(tmp_path):
    """Crashed-handoff-coordinator backstop: a 2PC hold streamed
    mid-handoff keeps its ORIGINAL coordinator-stamped expires_at on the
    new owner, so even if both the 2PC and the handoff coordinator die
    forever, the hold releases by the same deterministic TTL arithmetic —
    on a group that never saw the original reserve."""
    t_apply, t_db = _mk(tmp_path)
    held = _ref_in_group(1, count=2, salt="ttl-stream")
    assert t_apply(InstallShardStateCommand(
        (), ((serialize(held).bytes, TX_A.bytes, T0 + 5.0),),
        1, 1, 2, 1, b"i0")) is None
    assert t_apply(ShardFenceCommand(1, 1, 2, 1, "activate", b"f0")) is None
    assert _reserved(t_db) == 1
    # Inside the hold: enforced on the new owner exactly as on the old.
    assert t_apply(PutAllCommand((held,), TX_B, CALLER, b"p0",
                                 issued_at=T0 + 4.9)) is BUSY
    # Stamped at/past the original expiry: the deterministic steal.
    assert t_apply(PutAllCommand((held,), TX_B, CALLER, b"p1",
                                 issued_at=T0 + 5.0)) is None
    assert (_reserved(t_db), _committed(t_db)) == (0, 1)


def test_live_split_old_epoch_bounce_rederive_exactly_once(tmp_path):
    """The tentpole end to end, deterministically: a 1 -> 2 split over two
    in-process nodes (group 1 booted as a PENDING target). An old-epoch
    submission hits the sealed source and surfaces WrongShardEpochException
    — resubmitting to the same group can never succeed — then the
    plan-driven handoff runs to completion through the node loop, routing
    re-derives, and the SAME transactions converge exactly once with the
    moved history answering on the new owner."""
    import os as _os

    cfg = ShardConfig(count=1, groups=(("ShardA",), ("ShardB",)),
                      reserve_ttl_s=15.0)
    nodes = []
    for name in SHARD_NAMES:
        nodes.append(Node(NodeConfig(
            name=name, base_dir=tmp_path / name, notary="raft-simple",
            raft_cluster=(name,), network_map=tmp_path / "netmap.json",
            notary_shards=cfg)).start())
    try:
        for n in nodes:
            n.refresh_netmap()
        wait_group_leaders(nodes)
        prov = nodes[0].uniqueness_provider
        assert (prov.count, prov.epoch) == (1, 0)
        moved = _ref_in_group(1, count=2, salt="live-move")
        tx_m = SecureHash.sha256(b"live-moved-tx")
        # Pre-split: EVERYTHING routes to group 0 (count=1 fast path).
        assert drive(nodes, prov.commit_async(
            (moved,), tx_m, nodes[0].identity)) is True
        assert nodes[0].uniqueness_provider.committed_count == 1

        # Seal group 0 by hand (the coordinator's first step) so the
        # old-epoch bounce is deterministic, not a race with the stream.
        nodes[0].raft_member.submit(
            ShardFenceCommand(0, 1, 2, 1, "seal", _os.urandom(16)))

        def _sealed():
            f = prov._read_fence()
            return True if f and f["mode"] == "sealed" else None

        drive(nodes, _sealed)
        moved2 = _ref_in_group(1, count=2, salt="live-move-2")
        tx_2 = SecureHash.sha256(b"live-post-split-tx")
        with pytest.raises(WrongShardEpochException):
            drive(nodes, prov.commit_async(
                (moved2,), tx_2, nodes[0].identity))
        assert prov.metrics["wrong_epoch"] >= 1

        # Publish the plan; the node loop picks it up off the netmap and
        # the source leader re-runs seal -> stream -> activate (idempotent
        # over the manual seal) to completion.
        publish_reshard_plan(tmp_path / "netmap.json", 1, 1, 2,
                             nodes[0].identity.owning_key)

        def _adopted():
            done = all(
                n.uniqueness_provider.epoch >= 1
                and n.uniqueness_provider.count == 2 for n in nodes)
            return True if done else None

        drive(nodes, _adopted, timeout=30.0)

        # Re-derived routing: the bounced tx now lands on group 1 and
        # commits; the pre-split spend is idempotent for its own tx and
        # FINAL for a thief — served by the NEW owner from streamed state.
        assert drive(nodes, prov.commit_async(
            (moved2,), tx_2, nodes[0].identity)) is True
        assert drive(nodes, prov.commit_async(
            (moved,), tx_m, nodes[0].identity)) is True
        with pytest.raises(UniquenessException):
            drive(nodes, prov.commit_async(
                (moved,), SecureHash.sha256(b"live-thief"),
                nodes[0].identity))
        # Exactly-once across the ledgers: each spend exactly one row, the
        # moved history purged from the source.
        assert nodes[0].uniqueness_provider.committed_count == 0
        assert nodes[1].uniqueness_provider.committed_count == 2
        assert prov.stamp()["epoch"] == 1
        assert nodes[0].uniqueness_provider.metrics["resharded"] == 1
    finally:
        for n in nodes:
            n.stop()


# -- multi-process soaks (out of tier-1) -------------------------------------


@pytest.mark.slow
def test_chaos_sharded_exactly_once_under_faults(tmp_path):
    """2 groups x 3 members, lossy transport plan armed, group 0's LEADER
    killed mid-burst, 25% of the mix forced cross-shard: the client-side
    outcomes AND the cluster-side ledger row count must agree exactly-once,
    with zero reservation rows surviving the drain."""
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    r = run_chaos_loadtest(plan="lossy", n_tx=24, cluster_size=3,
                           kill_leader=True, shards=2, cross_frac=0.25,
                           base_dir=str(tmp_path / "chaos"))
    assert r.shards == 2
    assert r.cross_requested > 0
    assert r.reserved_leaked == 0
    assert r.exactly_once, r.to_json()


@pytest.mark.slow
def test_multiprocess_shard_cluster_cross_mix(tmp_path):
    """Driver-booted 2-shard topology (real OS processes, RPC-driven
    firehose with a cross-shard mix): the MultiProcessResult ledger audit
    must balance — committed + cross_committed rows, nothing leaked."""
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    r = run_loadtest_multiprocess(
        n_tx=24, width=2, clients=1, notary="raft", cluster_size=1,
        inflight=8, shards=2, cross_frac=0.25,
        base_dir=str(tmp_path / "mp"))
    assert r.shards == 2
    assert r.cross_requested > 0
    assert r.ledger_committed == r.ledger_expected
    assert r.exactly_once, r.to_json()
