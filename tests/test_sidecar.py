"""Verification sidecar (crypto/sidecar.py + node/verify_client.py):
protocol parity vs the CPU oracle path, cross-client coalescing, deadline/
capacity flush, and the kill-sidecar degrade → cooldown re-probe →
exactly-once contract. Fast tier runs everything in-process over unix
sockets; the multi-node soak is @slow.
"""

import os
import shutil
import tempfile
import threading
import time

import numpy as np
import pytest

from corda_tpu.crypto import sidecar as sc
from corda_tpu.crypto.keys import KeyPair, SignatureError
from corda_tpu.crypto.provider import CpuVerifier, VerifyJob
from corda_tpu.crypto.sidecar import SidecarServer
from corda_tpu.flows.api import FlowLogic, VerifySigRequest, register_flow
from corda_tpu.node.config import BatchConfig, NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.node.verify_client import (SidecarError, SidecarVerifier,
                                          fetch_sidecar_stats)


@pytest.fixture
def sock_path():
    # Short /tmp path on purpose: AF_UNIX paths cap at ~108 bytes and
    # pytest's tmp_path nests deep enough to blow it.
    d = tempfile.mkdtemp(prefix="sct-", dir="/tmp")
    try:
        yield os.path.join(d, "s.sock")
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _server(sock_path, **kw):
    kw.setdefault("verifier", CpuVerifier())
    kw.setdefault("coalesce_us", 0)
    return SidecarServer(sock_path, **kw).start()


def _garbage(n):
    return [VerifyJob(bytes(32), bytes(32), bytes(64))] * n


def _corpus():
    """Accept AND reject lanes plus the malformed/unknown-scheme edges."""
    kp = KeyPair.generate(b"\x07" * 32)
    msg = b"sidecar-parity".ljust(32, b".")
    sig = kp.sign(msg)
    pk, raw = bytes(sig.by.encoded), bytes(sig.bytes)
    bad = raw[:5] + bytes([raw[5] ^ 1]) + raw[6:]
    kp2 = KeyPair.generate(b"\x08" * 32)
    msg2 = b"second-signer-much-longer-message-" * 3
    sig2 = kp2.sign(msg2)
    return [
        VerifyJob(pk, msg, raw),                        # accept
        VerifyJob(pk, msg, bad),                        # reject
        VerifyJob(bytes(sig2.by.encoded), msg2, bytes(sig2.bytes)),
        VerifyJob(b"\x01" * 31, msg, raw),              # malformed pk
        VerifyJob(pk, msg, raw[:63]),                   # malformed sig
        VerifyJob(pk, msg, raw, scheme="nope"),         # unknown scheme
        VerifyJob(pk, msg2, raw),                       # wrong message
    ]


# ---------------------------------------------------------------------------
# Wire codec
# ---------------------------------------------------------------------------


def test_wire_roundtrip_variable_length_messages():
    jobs = [VerifyJob(bytes([i]) * 32, b"m" * (i * 7), bytes([i]) * 64)
            for i in range(1, 6)]
    req_id, decoded = sc.decode_verify_request(
        sc.encode_verify_request(42, jobs))
    assert req_id == 42
    assert [(j.pubkey, j.message, j.sig) for j in decoded] == \
           [(j.pubkey, j.message, j.sig) for j in jobs]


def test_bucket_ladder_matches_kernel():
    assert sc.bucket_for(1) == 64
    assert sc.bucket_for(80) == 256
    assert sc.bucket_for(4096) == 4096
    assert sc.bucket_for(10 ** 9) == 65536


# ---------------------------------------------------------------------------
# Protocol parity vs CpuVerifier
# ---------------------------------------------------------------------------


def test_protocol_parity_vs_cpu_verifier(sock_path):
    srv = _server(sock_path)
    try:
        jobs = _corpus()
        cli = SidecarVerifier(sock_path, device_min_sigs=0)
        out = cli.verify_batch(jobs)
        want = CpuVerifier().verify_batch(jobs)
        assert np.array_equal(out, want), (out.tolist(), want.tolist())
        # Everything routed through the sidecar, nothing fell back.
        assert cli.device_batches == 1
        assert cli.host_batches == 0
        assert cli.fallbacks == 0
        # Malformed + unknown-scheme jobs stayed local: only the four
        # well-formed ed25519 jobs rode the wire.
        assert cli.sidecar_sigs == 4
        stats = srv.stats()
        assert stats["requests"] == 1
        assert stats["sigs"] == 4
    finally:
        srv.stop()


def test_stats_and_ping_endpoints(sock_path):
    srv = _server(sock_path)
    try:
        cli = SidecarVerifier(sock_path, device_min_sigs=0)
        cli.warm()  # OP_PING round trip
        stats = fetch_sidecar_stats(sock_path)
        assert stats["verifier"] == "cpu-openssl"
        assert stats["batches"] == 0
        assert stats["coalesce_us"] == 0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Coalescing scheduler
# ---------------------------------------------------------------------------


def test_cross_client_requests_coalesce_into_one_bucket(sock_path):
    # A generous window so both clients land inside it; capacity (4096)
    # never reached, so exactly one deadline flush serves both.
    srv = _server(sock_path, coalesce_us=300_000)
    try:
        clients = [SidecarVerifier(sock_path, device_min_sigs=0)
                   for _ in range(2)]
        barrier = threading.Barrier(2)
        outs = [None, None]

        def go(i):
            barrier.wait()
            outs[i] = clients[i].verify_batch(_garbage(40))

        threads = [threading.Thread(target=go, args=(i,)) for i in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert all(o is not None and len(o) == 40 and not o.any()
                   for o in outs)
        stats = srv.stats()
        assert stats["requests"] == 2
        assert stats["batches"] == 1  # ONE device dispatch for both
        assert stats["cross_request_batches"] == 1
        assert stats["sigs"] == 80
        assert stats["batch_sigs_hist"] == {"256": 1}  # pick_bucket(80)
    finally:
        srv.stop()


def test_deadline_flush_bounds_a_lonely_request(sock_path):
    srv = _server(sock_path, coalesce_us=150_000)
    try:
        cli = SidecarVerifier(sock_path, device_min_sigs=0)
        t0 = time.perf_counter()
        out = cli.verify_batch(_garbage(4))
        elapsed = time.perf_counter() - t0
        assert len(out) == 4
        # Held for company up to the deadline, then flushed alone.
        assert 0.10 <= elapsed < 1.5, elapsed
        assert srv.stats()["batches"] == 1
        assert srv.stats()["cross_request_batches"] == 0
    finally:
        srv.stop()


def test_capacity_flush_beats_the_deadline(sock_path):
    # The window is far longer than the client deadline: only the early
    # flush at bucket capacity can answer in time.
    srv = _server(sock_path, coalesce_us=30_000_000, max_sigs=64)
    try:
        cli = SidecarVerifier(sock_path, device_min_sigs=0,
                              deadline_ms=10_000.0)
        t0 = time.perf_counter()
        out = cli.verify_batch(_garbage(64))
        elapsed = time.perf_counter() - t0
        assert len(out) == 64
        assert elapsed < 5.0, elapsed
        assert srv.stats()["batches"] == 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Failure lanes: error reply, kill -> degrade -> re-probe
# ---------------------------------------------------------------------------


class _RaisingVerifier:
    name = "raising"

    def verify_batch(self, jobs):
        raise RuntimeError("device backend died")


def test_server_verifier_error_reply_falls_back_to_host(sock_path):
    srv = _server(sock_path, verifier=_RaisingVerifier())
    try:
        jobs = _corpus()
        cli = SidecarVerifier(sock_path, device_min_sigs=0)
        out = cli.verify_batch(jobs)
        # Infra fault never rejects: the host tier answered, correctly.
        assert np.array_equal(out, CpuVerifier().verify_batch(jobs))
        assert cli.fallbacks == 1
        assert cli.degraded == 1
        assert srv.stats()["errors"] == 1
    finally:
        srv.stop()


def test_kill_sidecar_degrades_then_cooldown_reprobe_reopens(sock_path):
    srv = _server(sock_path)
    jobs = _corpus()
    want = CpuVerifier().verify_batch(jobs)
    cli = SidecarVerifier(sock_path, device_min_sigs=0,
                          reprobe_cooldown_s=0.05)
    try:
        assert np.array_equal(cli.verify_batch(jobs), want)
        assert cli.device_batches == 1
        srv.stop()  # kill the sidecar

        out = cli.verify_batch(jobs)
        assert np.array_equal(out, want)  # host tier answered
        assert cli.fallbacks == 1
        assert cli.degraded == 1
        assert cli.host_batches >= 1
        assert cli.device_gate is not None and not cli.device_gate.is_set()

        # While the gate is closed, batches host-route WITHOUT retrying
        # the socket (no new fallbacks).
        assert np.array_equal(cli.verify_batch(jobs), want)
        assert cli.fallbacks == 1

        # Resurrect the server on the same path: the cooldown re-probe
        # round-trips a garbage batch and re-opens the gate.
        srv = _server(sock_path)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not cli.device_gate.is_set():
            time.sleep(0.02)
        assert cli.device_gate.is_set(), "re-probe never re-opened the gate"
        assert cli.reprobes_ok >= 1

        before = cli.device_batches
        assert np.array_equal(cli.verify_batch(jobs), want)
        assert cli.device_batches == before + 1  # sidecar tier again
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Node-level wiring: config, assembly, flows, kill mid-traffic
# ---------------------------------------------------------------------------


@register_flow
class SidecarSigFlow(FlowLogic):
    def __init__(self, pubkey: bytes, message: bytes, sig_bytes: bytes):
        self.pubkey = pubkey
        self.message = message
        self.sig_bytes = sig_bytes

    def call(self):
        yield VerifySigRequest(self.pubkey, self.message, self.sig_bytes,
                               description="SidecarSigFlow")
        return "verified"


def _sig_args(seed=b"\x07" * 32, message=b"sidecar-verify-me".ljust(32, b".")):
    kp = KeyPair.generate(seed)
    sig = kp.sign(message)
    return bytes(sig.by.encoded), bytes(message), bytes(sig.bytes)


def _make_node(tmp_path, name="SidecarNode", **batch_kw):
    return Node(NodeConfig(
        name=name,
        base_dir=tmp_path / name,
        network_map=tmp_path / "netmap.json",
        batch=BatchConfig(max_wait_ms=0.5, **batch_kw),
    )).start()


def _pump(node, predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        node.run_once(timeout=0.01)
        if predicate():
            return
    raise AssertionError("node did not settle in time")


def test_batch_config_parses_sidecar_keys(tmp_path):
    cfg = NodeConfig.from_dict({
        "name": "N", "base_dir": str(tmp_path),
        "batch": {"sidecar": "/tmp/x.sock", "sidecar_deadline_ms": 750.0},
    })
    assert cfg.batch.sidecar == "/tmp/x.sock"
    assert cfg.batch.sidecar_deadline_ms == 750.0
    # Disabled path defaults: bit-identical config to before.
    cfg2 = NodeConfig.from_dict({"name": "N", "base_dir": str(tmp_path)})
    assert cfg2.batch.sidecar == ""
    assert cfg2.batch.sidecar_deadline_ms == 2000.0


def test_node_assembly_without_sidecar_is_unchanged(tmp_path, monkeypatch):
    monkeypatch.delenv("CORDA_TPU_SIDECAR", raising=False)
    node = _make_node(tmp_path)
    try:
        assert node.smm.verifier.name == "cpu-openssl"
    finally:
        node.stop()


def test_node_assembly_env_override_selects_sidecar(tmp_path, monkeypatch):
    monkeypatch.setenv("CORDA_TPU_SIDECAR", "/tmp/env-sidecar.sock")
    node = _make_node(tmp_path, name="EnvSidecarNode")
    try:
        assert node.smm.verifier.name == "sidecar"
        assert node.smm.verifier.address == "/tmp/env-sidecar.sock"
    finally:
        node.stop()


def test_node_flows_verify_through_sidecar_and_survive_kill(
        tmp_path, sock_path, monkeypatch):
    # min_sigs=1: even single-sig flow batches ship to the server — the
    # whole point of the sidecar is that MICRO-batches flow out.
    monkeypatch.setenv("CORDA_TPU_SIDECAR_MIN_SIGS", "1")
    srv = _server(sock_path)
    node = _make_node(tmp_path, sidecar=sock_path)
    try:
        verifier = node.smm.verifier
        assert verifier.name == "sidecar"
        pk, msg, sig = _sig_args()
        good = node.start_flow(SidecarSigFlow(pk, msg, sig))
        bad = node.start_flow(
            SidecarSigFlow(pk, msg, bytes([sig[0] ^ 1]) + sig[1:]))
        _pump(node, lambda: good.result.done and bad.result.done)
        assert good.result.result() == "verified"
        with pytest.raises(SignatureError):
            bad.result.result()
        assert verifier.device_batches >= 1  # the sidecar served them
        assert srv.stats()["sigs"] >= 2

        # Kill the sidecar mid-traffic: new flows must still complete,
        # exactly once each, with correct verdicts — via the host tier.
        srv.stop()
        good2 = node.start_flow(SidecarSigFlow(pk, msg, sig))
        bad2 = node.start_flow(
            SidecarSigFlow(pk, msg, bytes([sig[0] ^ 1]) + sig[1:]))
        _pump(node, lambda: good2.result.done and bad2.result.done)
        assert good2.result.result() == "verified"
        with pytest.raises(SignatureError):
            bad2.result.result()
        assert verifier.fallbacks >= 1
        assert verifier.degraded >= 1
        # Exactly-once: each flow finished one time (no dup delivery).
        assert node.smm.metrics.get("finished") == 4
    finally:
        node.stop()
        srv.stop()


def test_node_metrics_carry_sidecar_and_effective_min_sigs(
        tmp_path, sock_path, monkeypatch):
    from corda_tpu.node.rpc import NodeRpcOps

    monkeypatch.setenv("CORDA_TPU_SIDECAR_MIN_SIGS", "1")
    srv = _server(sock_path)
    node = _make_node(tmp_path, sidecar=sock_path)
    try:
        m = NodeRpcOps(node).node_metrics()
        assert m["verifier"] == "sidecar"
        assert m["sidecar"]["address"] == sock_path
        assert m["sidecar"]["min_sigs"] == 1
        # Satellite: the EFFECTIVE crossover is stamped (== the live value
        # when no adaptive adjustment has happened yet).
        assert m["verify_effective_min_sigs"] == 1
    finally:
        node.stop()
        srv.stop()

    # Sidecar-less node: same schema, sidecar None, effective falls back
    # to the verifier's device_min_sigs (None for cpu).
    monkeypatch.delenv("CORDA_TPU_SIDECAR", raising=False)
    node2 = _make_node(tmp_path, name="PlainNode")
    try:
        m2 = NodeRpcOps(node2).node_metrics()
        assert m2["sidecar"] is None
        assert "verify_effective_min_sigs" in m2
    finally:
        node2.stop()


def test_member_stamp_reports_occupancy_and_sidecar():
    from corda_tpu.tools.loadtest import _member_stamp

    stamp = _member_stamp({
        "verifier": "sidecar", "verify_device_batches": 3,
        "verify_host_batches": 1, "verify_effective_min_sigs": 16,
        "verify_static_min_sigs": 16,
        "sidecar": {"batches": 3, "fallbacks": 0},
    }, device="cpu")
    assert stamp["device_occupancy"] == 0.75
    assert stamp["effective_min_sigs"] == 16
    assert stamp["sidecar"] == {"batches": 3, "fallbacks": 0}
    # No batches at all -> occupancy is honestly unknown, not 0.
    empty = _member_stamp({}, device="cpu")
    assert empty["device_occupancy"] is None
    assert empty["sidecar"] is None


# ---------------------------------------------------------------------------
# Satellite: CPU-signature-keyed compile cache
# ---------------------------------------------------------------------------


def test_host_cpu_signature_keys_the_cache_dirs(monkeypatch):
    from corda_tpu.ops import default_jax_cache_dir, host_cpu_signature
    from corda_tpu.testing.driver import _node_env

    sig = host_cpu_signature()
    assert len(sig) == 8
    assert sig == host_cpu_signature()  # deterministic
    int(sig, 16)  # hex
    assert default_jax_cache_dir().endswith(f"_{sig}")
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    env = _node_env("accelerator")
    assert env["JAX_COMPILATION_CACHE_DIR"] == default_jax_cache_dir()
    assert _node_env("cpu").get("JAX_PLATFORMS") == "cpu"


# ---------------------------------------------------------------------------
# Multi-node soak (@slow): the real multiprocess harness with --sidecar
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_multiprocess_loadtest_with_sidecar_commits_and_stamps():
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    res = run_loadtest_multiprocess(
        n_tx=24, width=4, clients=1, notary="raft-validating",
        cluster_size=3, verifier="cpu", notary_device="cpu",
        sidecar=True, max_seconds=300.0)
    assert res.tx_committed == 24
    assert res.sidecar is not None and "error" not in res.sidecar
    assert res.sidecar["sigs"] > 0
    assert res.sidecar["requests"] > 0
    member_sidecars = [s.get("sidecar") for s in res.node_stamps.values()]
    assert any(s and s.get("batches", 0) > 0 for s in member_sidecars), (
        "no member shipped a batch to the sidecar")
    assert all(not (s or {}).get("fallbacks") for s in member_sidecars)
