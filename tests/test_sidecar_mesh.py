"""Mesh-owning sidecar (crypto/sidecar.py devices=N + ops/sharded.py pack/
dispatch split): bit-exact parity vs the single-device and host tiers,
pad-lane masking and per-device occupancy attribution, graceful degrade when
the mesh cannot be built, and the adaptive coalesce_us policy.

Runs on the conftest's virtual 8-device CPU mesh — no hardware needed; the
CPU backend is the conformance twin of the TPU path.
"""

import os
import shutil
import tempfile
import time

import numpy as np
import pytest

import jax

from corda_tpu.crypto import sidecar as sc
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.provider import CpuVerifier, MeshVerifier, VerifyJob
from corda_tpu.crypto.sidecar import SidecarServer
from corda_tpu.node.verify_client import SidecarVerifier, fetch_sidecar_stats

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest's 8-device virtual CPU mesh")


@pytest.fixture
def sock_path():
    # Short /tmp path: AF_UNIX caps at ~108 bytes, pytest tmp_path nests deep.
    d = tempfile.mkdtemp(prefix="scm-", dir="/tmp")
    try:
        yield os.path.join(d, "s.sock")
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _jobs(n, reject_every=5):
    """n well-formed ed25519 jobs, every reject_every-th sig corrupted —
    accept AND reject lanes so pad masking can't hide a wrong answer."""
    out = []
    for i in range(n):
        kp = KeyPair.generate(bytes([(i % 250) + 1]) * 32)
        msg = (b"mesh-%04d" % i).ljust(32, b".")
        sig = bytes(kp.sign(msg).bytes)
        if i % reject_every == reject_every - 1:
            sig = sig[:7] + bytes([sig[7] ^ 0x20]) + sig[8:]
        out.append(VerifyJob(bytes(kp.sign(msg).by.encoded), msg, sig))
    return out


def _wait_gate(address, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        stats = fetch_sidecar_stats(address)
        if stats.get("device_ready") or stats.get("warm_error"):
            return stats
        time.sleep(0.02)
    raise AssertionError("sidecar warm gate never settled")


# ---------------------------------------------------------------------------
# The mesh path end to end: parity, pad masking, occupancy attribution
# ---------------------------------------------------------------------------


@needs_mesh
def test_mesh_sidecar_parity_pad_masking_and_stats(sock_path):
    srv = SidecarServer(
        sock_path,
        verifier=MeshVerifier(n_devices=8, device_min_sigs=0),
        coalesce_us=0, devices=8).start()
    try:
        stats = _wait_gate(sock_path)
        assert stats["warm_error"] is None
        assert stats["mesh_devices"] == 8  # PROVEN by the warm thread

        # 19 lanes -> bucket 64 on an 8-wide mesh: 45 pad lanes that must
        # verify False without leaking into (or out of) the real lanes.
        jobs = _jobs(19)
        want = CpuVerifier().verify_batch(jobs)
        assert want.any() and not want.all()  # accepts AND rejects
        cli = SidecarVerifier(sock_path, device_min_sigs=0,
                              deadline_ms=120_000.0, devices=8)
        out = cli.verify_batch(jobs)
        assert np.array_equal(out, want), (out.tolist(), want.tolist())
        assert cli.fallbacks == 0
        assert cli.last_tier == "device"

        stats = srv.stats()
        assert stats["device_batches"] == 1
        assert stats["host_batches"] == 0
        assert stats["device_occupancy"] == 1.0
        # The scheduler packed it (pipelined path), the executor dispatched.
        assert stats["packed_batches"] == 1
        assert stats["pack_s_total"] > 0.0
        # Exact pad attribution from the packed handle.
        assert stats["device_lanes"] == 64
        assert stats["pad_lanes"] == 64 - 19
        assert stats["pad_fraction"] == round(45 / 64, 4)
        assert stats["per_device_occupancy"] == round(19 / 64, 4)
        # 64 lanes / 8 devices = 8 lanes per device, once.
        assert stats["per_device_batch_sigs_hist"] == {"8": 1}
        assert stats["devices"] == 8

        # Client-side stamp embeds the server snapshot for node_metrics.
        side = cli.sidecar_stats()
        assert side["devices"] == 8
        assert side["server"]["mesh_devices"] == 8
        assert side["server"]["per_device_occupancy"] == round(19 / 64, 4)
    finally:
        srv.stop()


@needs_mesh
def test_mesh_matches_single_device_tier_bit_exact(sock_path):
    # Same corpus through the mesh sidecar and the single-device verifier:
    # verdicts must be IDENTICAL (the sharded graph reuses the single-chip
    # graph functions — drift would mean the tiers forked).
    from corda_tpu.crypto.provider import JaxVerifier

    jobs = _jobs(37, reject_every=4)
    single = JaxVerifier(device_min_sigs=0).verify_batch(jobs)
    srv = SidecarServer(
        sock_path,
        verifier=MeshVerifier(n_devices=8, device_min_sigs=0),
        coalesce_us=0, devices=8).start()
    try:
        _wait_gate(sock_path)
        cli = SidecarVerifier(sock_path, device_min_sigs=0,
                              deadline_ms=120_000.0)
        out = cli.verify_batch(jobs)
        assert np.array_equal(out, single)
        assert np.array_equal(out, CpuVerifier().verify_batch(jobs))
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Degrade lanes: mesh unavailable / devices=1
# ---------------------------------------------------------------------------


def test_unbuildable_mesh_degrades_to_exact_host_tier(sock_path):
    # 64 devices don't exist: the warm thread must record WHY, keep the
    # gate closed forever, and every batch must host-route to the
    # oracle-exact tier — degraded throughput, never a wrong answer.
    srv = SidecarServer(
        sock_path,
        verifier=MeshVerifier(n_devices=64, device_min_sigs=0),
        coalesce_us=0, devices=64).start()
    try:
        stats = _wait_gate(sock_path)
        assert stats["warm_error"] and "64" in stats["warm_error"]
        assert stats["mesh_devices"] is None
        assert stats["device_ready"] is False

        jobs = _jobs(12)
        cli = SidecarVerifier(sock_path, device_min_sigs=0,
                              deadline_ms=60_000.0)
        out = cli.verify_batch(jobs)
        assert np.array_equal(out, CpuVerifier().verify_batch(jobs))
        assert cli.fallbacks == 0  # the SERVER answered (host tier)
        assert cli.last_tier == "host"

        stats = srv.stats()
        assert stats["device_batches"] == 0
        assert stats["host_batches"] == 1
        assert stats["packed_batches"] == 0  # gate closed -> pack refused
        assert stats["device_lanes"] == 0 and stats["pad_lanes"] == 0
    finally:
        srv.stop()


def test_devices_one_keeps_single_device_verifier():
    # devices<=1 must keep the PR-5 tiers bit-identical; only devices>1
    # upgrades a jax tier to the mesh; cpu ignores devices entirely.
    make = SidecarServer._make_server_verifier
    assert make("jax", 1).name == "jax-batch"
    assert make("jax", 0).name == "jax-batch"
    assert make("jax", 8).name == "jax-sharded"
    assert make("jax", 8).n_devices == 8
    assert make("jax-shadow", 4).shadow_rate == 0.05
    assert make("jax-sharded", 2).n_devices == 2
    assert make("cpu", 8).name == "cpu-openssl"


def test_pad_to_devices_arithmetic():
    assert sc.pad_to_devices(19, 8) == 24
    assert sc.pad_to_devices(64, 8) == 64
    assert sc.pad_to_devices(1, 8) == 8
    assert sc.pad_to_devices(0, 8) == 8
    assert sc.pad_to_devices(65, 8) == 72
    assert sc.pad_to_devices(100, 1) == 100
    # Every kernel bucket is already a multiple of 1/2/4/8: mesh padding
    # beyond the bucket ladder is zero for power-of-two meshes.
    for b in sc.BUCKETS:
        for ndev in (1, 2, 4, 8):
            assert sc.pad_to_devices(b, ndev) == b


# ---------------------------------------------------------------------------
# Adaptive coalesce_us (no timing: the policy is driven directly)
# ---------------------------------------------------------------------------


def _adapt_server(coalesce_us, max_sigs=4096):
    # __init__ binds nothing; start() is never called — pure policy unit.
    return SidecarServer("/tmp/unused-adapt.sock", verifier=CpuVerifier(),
                         coalesce_us=coalesce_us, max_sigs=max_sigs,
                         adaptive_coalesce=True)


def _feed(srv, n_requests, n_sigs, batches=sc.ADAPT_WINDOW):
    for _ in range(batches):
        srv._adapt_observe(n_requests, n_sigs)


def test_adaptive_coalesce_shrinks_when_batches_fill_early():
    srv = _adapt_server(1000)
    _feed(srv, n_requests=4, n_sigs=2048)  # mean >= max_sigs/2
    assert srv.coalesce_us == 750  # 1000 * ADAPT_SHRINK
    assert srv.coalesce_adjustments == 1
    assert srv.coalesce_us_initial == 1000  # the initial value is stamped


def test_adaptive_coalesce_grows_only_while_coalescing():
    srv = _adapt_server(1000)
    # Small batches but NO cross-request coalescing (1 request per batch):
    # a longer window would not attract company — no change.
    _feed(srv, n_requests=1, n_sigs=100)
    assert srv.coalesce_us == 1000
    # Same fill WITH coalescing: grow toward the ceiling.
    _feed(srv, n_requests=3, n_sigs=100)
    assert srv.coalesce_us == 1500  # 1000 * ADAPT_GROW
    # From zero, growth seeds at ADAPT_SEED_US (0 * anything stays 0).
    srv0 = _adapt_server(0)
    _feed(srv0, n_requests=2, n_sigs=64)
    assert srv0.coalesce_us == sc.ADAPT_SEED_US


def test_adaptive_coalesce_hysteresis_band_and_ceiling():
    srv = _adapt_server(1000)
    # Between max_sigs/4 and max_sigs/2: the hysteresis band — no change.
    _feed(srv, n_requests=4, n_sigs=1500)
    assert srv.coalesce_us == 1000
    assert srv.coalesce_adjustments == 0
    # Growth is capped at ADAPT_CEILING_US.
    srv_hi = _adapt_server(19_000)
    _feed(srv_hi, n_requests=2, n_sigs=64)
    assert srv_hi.coalesce_us == sc.ADAPT_CEILING_US


def test_adaptive_coalesce_off_by_default(sock_path):
    srv = SidecarServer(sock_path, verifier=CpuVerifier(), coalesce_us=0)
    try:
        assert srv.adaptive_coalesce is False
        stats_keys = srv.stats()
        assert stats_keys["adaptive_coalesce"] is False
        assert stats_keys["coalesce_adjustments"] == 0
    finally:
        pass  # never started — nothing to stop
