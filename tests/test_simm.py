"""The fixed-point SIMM margin model: determinism, sensitivity structure,
100-trade two-node agreement, and tamper rejection.

Reference capability: samples/simm-valuation-demo/.../analytics/
AnalyticsEngine.kt (per-trade curve sensitivities + ISDA-SIMM aggregation)
driven by flows/SimmFlow.kt's independent-compute-then-agree protocol.
"""

import random

from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.testing.mock_network import MockNetwork
from corda_tpu.tools import simm
from corda_tpu.tools.simm import IRSTrade


def _random_portfolio(n: int, seed: int = 42):
    rng = random.Random(seed)
    return tuple(
        IRSTrade(
            notional=rng.choice([-1, 1]) * rng.randrange(100_000, 5_000_000),
            fixed_rate_bp=rng.randrange(50, 600),
            maturity_days=rng.randrange(180, 10_000),
        )
        for _ in range(n))


def test_margin_is_deterministic_and_integer():
    trades = _random_portfolio(100)
    a = simm.initial_margin(trades, 2_5000)
    b = simm.initial_margin(tuple(trades), 2_5000)  # fresh tuple, same data
    assert isinstance(a, int) and a == b
    assert a > 0
    # order independence: sensitivities sum, so shuffling cannot matter
    shuffled = list(trades)
    random.Random(1).shuffle(shuffled)
    assert simm.initial_margin(tuple(shuffled), 2_5000) == a


def test_sensitivity_structure():
    # A receive-fixed swap loses value when rates rise: every tenor bucket
    # at or before maturity has non-positive sensitivity, and buckets
    # strictly beyond maturity have none.
    trade = IRSTrade(1_000_000, 250, 3 * 365)
    curve = simm.curve_from_fix(2_5000)
    sens = simm.trade_sensitivities(trade, curve)
    assert any(s < 0 for s in sens)
    beyond = [k for k, t in enumerate(simm.TENOR_DAYS)
              if t > trade.maturity_days]
    assert all(sens[k] == 0 for k in beyond)
    # Pay-fixed is the mirror image.
    mirrored = simm.trade_sensitivities(
        IRSTrade(-1_000_000, 250, 3 * 365), curve)
    assert mirrored == tuple(-s for s in sens)


def test_margin_subadditive_for_offsetting_trades():
    # Opposite positions hedge: margin(combined) < margin(a) + margin(b) —
    # the correlation aggregation is doing its job.
    a = (IRSTrade(2_000_000, 250, 5 * 365),)
    b = (IRSTrade(-2_000_000, 250, 5 * 365),)
    both = a + b
    assert simm.initial_margin(both, 2_5000) == 0  # exact hedge cancels
    tilted = (IRSTrade(2_000_000, 250, 5 * 365),
              IRSTrade(-1_000_000, 250, 5 * 365))
    assert 0 < simm.initial_margin(tilted, 2_5000) \
        < simm.initial_margin(a, 2_5000)


def test_rho_matrix_is_symmetric_psd_shape():
    n = len(simm.TENOR_DAYS)
    for k in range(n):
        assert simm.RHO_PCT[k][k] == 100
        for l in range(n):
            assert simm.RHO_PCT[k][l] == simm.RHO_PCT[l][k]
            assert 0 < simm.RHO_PCT[k][l] <= 100


def test_hundred_trade_portfolio_agrees_on_ledger():
    # VERDICT round-3 item 10's bar: two nodes compute IDENTICAL margins on
    # a 100-trade portfolio and ledger the agreed number.
    from corda_tpu.contracts.structures import Command
    from corda_tpu.flows.oracle import FixOf, RateOracle
    from corda_tpu.tools.portfolio import (
        PortfolioState,
        SimmValuationFlow,
        ValueCommand,
        compute_valuation,
        install_simm_responder,
    )
    from corda_tpu.transactions.builder import TransactionBuilder

    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        a = net.create_node("Dealer A")
        b = net.create_node("Dealer B")
        o = net.create_node("Oracle")
        rate_ref = FixOf("IM-RATE", 20_200, "1D")
        RateOracle(o.smm, o.key, {rate_ref: 2_5000})
        install_simm_responder(b.smm)

        trades = _random_portfolio(100)
        portfolio = PortfolioState(
            party_a=a.identity, party_b=b.identity, oracle=o.identity,
            rate_ref=rate_ref, trades=trades)
        tx = TransactionBuilder(notary=notary.identity)
        tx.add_output_state(portfolio)
        tx.add_command(Command(ValueCommand(), (a.identity.owning_key,
                                                b.identity.owning_key)))
        tx.sign_with(a.key)
        tx.sign_with(b.key)
        stx = tx.to_signed_transaction()
        a.record_transaction(stx)
        b.record_transaction(stx)

        handle = a.start_flow(SimmValuationFlow(stx.tx.out_ref(0).ref))
        net.run_network()
        final = handle.result.result()
        valued = [s.data for s in final.tx.outputs
                  if isinstance(s.data, PortfolioState)][0]
        assert valued.valuation == compute_valuation(trades, 2_5000) > 0
    finally:
        net.stop_nodes()


def test_tampered_portfolio_refuses_to_ledger():
    # The two sides hold DIFFERENT versions of "the" portfolio (one trade's
    # notional doctored on B's copy): independent recomputation diverges,
    # the responder refuses, and nothing reaches the ledger.
    from dataclasses import replace

    from corda_tpu.contracts.structures import Command
    from corda_tpu.flows.api import FlowException
    from corda_tpu.flows.oracle import FixOf, RateOracle
    from corda_tpu.tools.portfolio import (
        PortfolioState,
        SimmValuationFlow,
        ValueCommand,
        install_simm_responder,
    )
    from corda_tpu.transactions.builder import TransactionBuilder

    import pytest

    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        a = net.create_node("Dealer A")
        b = net.create_node("Dealer B")
        o = net.create_node("Oracle")
        rate_ref = FixOf("IM-RATE", 20_200, "1D")
        RateOracle(o.smm, o.key, {rate_ref: 2_5000})
        install_simm_responder(b.smm)

        trades = _random_portfolio(10)

        def record_with(node, tr):
            portfolio = PortfolioState(
                party_a=a.identity, party_b=b.identity, oracle=o.identity,
                rate_ref=rate_ref, trades=tr,
                uid=__import__(
                    "corda_tpu.contracts.structures",
                    fromlist=["UniqueIdentifier"],
                ).UniqueIdentifier(external_id="shared", id=b"\x01" * 16))
            tx = TransactionBuilder(notary=notary.identity)
            tx.add_output_state(portfolio)
            tx.add_command(Command(ValueCommand(), (a.identity.owning_key,
                                                    b.identity.owning_key)))
            tx.sign_with(a.key)
            tx.sign_with(b.key)
            stx = tx.to_signed_transaction()
            node.record_transaction(stx)
            return stx

        stx_a = record_with(a, trades)
        doctored = (replace(trades[0], notional=trades[0].notional * 2),
                    ) + trades[1:]
        record_with(b, doctored)

        handle = a.start_flow(SimmValuationFlow(stx_a.tx.out_ref(0).ref))
        net.run_network()
        # The doctored trades change the portfolio's content-addressed ref,
        # so B cannot even load A's claimed portfolio: refusal at the first
        # gate (B's flow fails; A sees the session die unfed).
        with pytest.raises(FlowException):
            handle.result.result()
        # Nothing new reached B's ledger beyond its setup transaction.
        assert len(b.services.vault_service.unconsumed_states(
            PortfolioState)) == 1
    finally:
        net.stop_nodes()


def test_diverging_valuations_refuse_to_ledger(monkeypatch):
    # Same shared portfolio, but the two sides' model runs disagree (a
    # doctored engine on one side — injected by making successive
    # compute_valuation calls return different numbers). The agree step
    # must refuse and nothing reaches the ledger.
    from corda_tpu.contracts.structures import Command
    from corda_tpu.flows.api import FlowException
    from corda_tpu.flows.oracle import FixOf, RateOracle
    from corda_tpu.tools import portfolio as portfolio_mod
    from corda_tpu.tools.portfolio import (
        PortfolioState,
        SimmValuationFlow,
        ValueCommand,
        install_simm_responder,
    )
    from corda_tpu.transactions.builder import TransactionBuilder

    import pytest

    net = MockNetwork(verifier=CpuVerifier())
    try:
        notary = net.create_notary_node("Notary")
        a = net.create_node("Dealer A")
        b = net.create_node("Dealer B")
        o = net.create_node("Oracle")
        rate_ref = FixOf("IM-RATE", 20_200, "1D")
        RateOracle(o.smm, o.key, {rate_ref: 2_5000})
        install_simm_responder(b.smm)

        portfolio = PortfolioState(
            party_a=a.identity, party_b=b.identity, oracle=o.identity,
            rate_ref=rate_ref, trades=_random_portfolio(5))
        tx = TransactionBuilder(notary=notary.identity)
        tx.add_output_state(portfolio)
        tx.add_command(Command(ValueCommand(), (a.identity.owning_key,
                                                b.identity.owning_key)))
        tx.sign_with(a.key)
        tx.sign_with(b.key)
        stx = tx.to_signed_transaction()
        a.record_transaction(stx)
        b.record_transaction(stx)

        answers = iter([1_000_000, 1_000_001])  # A's run, then B's run
        monkeypatch.setattr(portfolio_mod, "compute_valuation",
                            lambda trades, rate: next(answers))
        handle = a.start_flow(SimmValuationFlow(stx.tx.out_ref(0).ref))
        net.run_network()
        with pytest.raises(FlowException, match="diverge"):
            handle.result.result()
        for node in (a, b):
            states = node.services.vault_service.unconsumed_states(
                PortfolioState)
            assert len(states) == 1
            assert states[0].state.data.valuation is None  # never valued
    finally:
        net.stop_nodes()
