"""Invariant analyzer: tier-1 gate + rule-engine coverage.

The first test is the merge-blocker: zero live findings over the shipped
tree. The rest prove each rule actually fires (a lint pass that never
fires enforces nothing), that suppressions demand reasons, and that the
baseline can only shrink.
"""

import json
import time
from pathlib import Path

from corda_tpu.analysis import (
    ALL_RULES,
    analyze_paths,
    analyze_source,
    baseline_entries_from_findings,
    load_baseline,
)
from corda_tpu.analysis.__main__ import main as cli_main

REPO = Path(__file__).resolve().parents[1]
TREE = REPO / "corda_tpu"

RAFT_PATH = "corda_tpu/node/services/raft.py"  # in-scope for wallclock rule


def _rules(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# The tier-1 gate
# ---------------------------------------------------------------------------


class TestTreeGate:
    def test_tree_has_zero_unbaselined_findings(self):
        t0 = time.perf_counter()
        report = analyze_paths([TREE])
        elapsed = time.perf_counter() - t0
        assert len(report.rules) >= 6
        assert report.checked_files > 100
        rendered = "\n".join(f.render() for f in report.findings)
        assert report.clean, f"live invariant findings:\n{rendered}"
        # ISSUE budget: the gate must stay cheap enough for tier-1.
        assert elapsed < 5.0, f"analyzer took {elapsed:.1f}s on the tree"

    def test_every_suppression_in_tree_was_exercised(self):
        # The tree carries reasoned allow() comments; each must suppress a
        # real finding (dead suppressions rot like dead baselines).
        report = analyze_paths([TREE])
        assert len(report.suppressed) >= 15

    def test_ingest_hot_path_is_in_scope_and_clean(self):
        # Round 15: the vectorized ingest plane is the highest-frequency
        # client-side loop in the tree — pin it in-scope explicitly so a
        # future exclude-list edit can't silently drop it from the gate
        # (no per-item jit/wallclock/silent-except regressions).
        report = analyze_paths([TREE / "tools" / "ingest.py",
                                TREE / "crypto" / "batch_sign.py",
                                TREE / "tools" / "loadgen.py"])
        assert report.checked_files == 3
        rendered = "\n".join(f.render() for f in report.findings)
        assert report.clean, f"ingest-plane findings:\n{rendered}"

    def test_checked_in_baseline_entries_are_live_files_with_reasons(self):
        # The baseline shrinks monotonically (round 12 resolved the last
        # two entries at source, so empty is the healthy end state); any
        # entry that IS carried must name a live file and a reason.
        path = REPO / "corda_tpu/analysis/baseline.json"
        assert path.exists(), "baseline file missing"
        for e in load_baseline(path):
            assert (REPO / e["path"]).exists(), e["path"]
            assert str(e.get("reason", "")).strip(), e


# ---------------------------------------------------------------------------
# Rule fixtures: violating + clean + suppressed (+ baselined)
# ---------------------------------------------------------------------------


class TestNoWallclockInApply:
    def test_replica_side_epoch_read_goes_red(self):
        src = (
            "import time as _time\n"
            "def _apply_reserve(db, cmd):\n"
            "    return _time.time() > cmd.issued_at + cmd.ttl_s\n"
        )
        report = analyze_source(src, RAFT_PATH)
        assert "no-wallclock-in-apply" in _rules(report)

    def test_monotonic_inside_apply_goes_red(self):
        src = (
            "import time\n"
            "def make_apply_command(db):\n"
            "    def helper():\n"
            "        return time.monotonic()\n"
            "    return helper\n"
        )
        report = analyze_source(src, RAFT_PATH)
        assert "no-wallclock-in-apply" in _rules(report)

    def test_monotonic_deadline_outside_apply_is_clean(self):
        src = (
            "import time as _time\n"
            "def poll(deadline):\n"
            "    return _time.monotonic() >= deadline\n"
        )
        report = analyze_source(src, RAFT_PATH)
        assert "no-wallclock-in-apply" not in _rules(report)

    def test_out_of_scope_file_is_ignored(self):
        src = "import time\nx = time.time()\n"
        report = analyze_source(src, "corda_tpu/tools/loadtest.py")
        assert "no-wallclock-in-apply" not in _rules(report)

    def test_real_coordinator_stamping_sites_stay_green(self):
        # The three ISSUE-named stamping sites fire the rule and are
        # absorbed by their reasoned allow() comments — never live.
        report = analyze_paths(
            [TREE / "node/services/sharding.py",
             TREE / "node/services/raft.py"],
            use_baseline=False)
        assert "no-wallclock-in-apply" not in _rules(report)
        stamped = [f for f in report.suppressed
                   if f.rule == "no-wallclock-in-apply"]
        assert len(stamped) >= 3


class TestNoSilentExcept:
    VIOLATION = (
        "def f(handler):\n"
        "    try:\n"
        "        handler()\n"
        "    except Exception:\n"
        "        pass\n"
    )

    def test_silent_pass_goes_red(self):
        report = analyze_source(self.VIOLATION, "corda_tpu/node/x.py")
        assert "no-silent-except" in _rules(report)

    def test_bare_except_goes_red(self):
        src = "def f(g):\n    try:\n        g()\n    except:\n        pass\n"
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-silent-except" in _rules(report)

    def test_narrowed_or_counting_handler_is_clean(self):
        src = (
            "def f(handler, metrics):\n"
            "    try:\n"
            "        handler()\n"
            "    except (LookupError, ValueError):\n"
            "        pass\n"
            "    try:\n"
            "        handler()\n"
            "    except Exception:\n"
            "        metrics['fails'] += 1\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-silent-except" not in _rules(report)

    def test_reasoned_allow_suppresses(self):
        src = (
            "def f(handler):\n"
            "    try:\n"
            "        handler()\n"
            "    # lint: allow(no-silent-except) demo tooling, retried next tick\n"
            "    except Exception:\n"
            "        pass\n"
        )
        report = analyze_source(src, "corda_tpu/tools/x.py")
        assert "no-silent-except" not in _rules(report)
        assert len(report.suppressed) == 1

    def test_baseline_absorbs_enumerated_site(self):
        entries = [{"rule": "no-silent-except", "path": "corda_tpu/node/x.py",
                    "code": "except Exception:", "count": 1,
                    "reason": "pre-existing, tracked"}]
        report = analyze_source(self.VIOLATION, "corda_tpu/node/x.py",
                                baseline_entries=entries)
        assert "no-silent-except" not in _rules(report)
        assert len(report.baselined) == 1


class TestNoJitInHotpath:
    def test_jit_inside_per_batch_function_goes_red(self):
        src = (
            "import jax\n"
            "def verify_batch(fn, xs):\n"
            "    return jax.jit(fn)(xs)\n"
        )
        report = analyze_source(src, "corda_tpu/ops/x.py")
        assert "no-jit-in-hotpath" in _rules(report)

    def test_mesh_construction_inside_function_goes_red(self):
        src = (
            "from jax.sharding import Mesh\n"
            "def dispatch(devs, xs):\n"
            "    return Mesh(devs, ('sigs',))\n"
        )
        report = analyze_source(src, "corda_tpu/ops/x.py")
        assert "no-jit-in-hotpath" in _rules(report)

    def test_module_level_and_cached_builders_are_clean(self):
        src = (
            "import functools\n"
            "import jax\n"
            "def _graph(x):\n"
            "    return x\n"
            "verify = jax.jit(_graph)\n"
            "@functools.lru_cache(maxsize=None)\n"
            "def builder(mesh):\n"
            "    return jax.jit(_graph)\n"
        )
        report = analyze_source(src, "corda_tpu/ops/x.py")
        assert "no-jit-in-hotpath" not in _rules(report)

    def test_module_level_jit_decorator_is_clean(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def verify_arrays(x):\n"
            "    return x\n"
        )
        report = analyze_source(src, "corda_tpu/ops/x.py")
        assert "no-jit-in-hotpath" not in _rules(report)


class TestNoBlockingUnderLock:
    def test_socket_send_under_lock_goes_red(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self, sock):\n"
            "        self._lock = threading.Lock()\n"
            "        self.sock = sock\n"
            "    def send(self, buf):\n"
            "        with self._lock:\n"
            "            self.sock.sendall(buf)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-blocking-under-lock" in _rules(report)

    def test_sqlite_under_designated_db_lock_is_exempt(self):
        src = (
            "class C:\n"
            "    def put(self, row):\n"
            "        with self.db.lock:\n"
            "            self.db.conn.execute('INSERT', row)\n"
            "            self.db.conn.commit()\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-blocking-under-lock" not in _rules(report)

    def test_copy_under_lock_send_outside_is_clean(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self, sock):\n"
            "        self._lock = threading.Lock()\n"
            "        self.sock = sock\n"
            "        self.queue = []\n"
            "    def flush(self):\n"
            "        with self._lock:\n"
            "            batch = list(self.queue)\n"
            "        self.sock.sendall(b''.join(batch))\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-blocking-under-lock" not in _rules(report)

    def test_condition_wait_is_exempt(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "    def park(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait(0.1)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-blocking-under-lock" not in _rules(report)

    def test_allow_on_with_line_suppresses(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self, sock):\n"
            "        self._lock = threading.Lock()\n"
            "        self.sock = sock\n"
            "    def send(self, buf):\n"
            "        # lint: allow(no-blocking-under-lock) this lock serializes the socket\n"
            "        with self._lock:\n"
            "            self.sock.sendall(buf)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "no-blocking-under-lock" not in _rules(report)
        assert len(report.suppressed) == 1


class TestLockOrder:
    def test_acquisition_cycle_goes_red(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._a:\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def g(self):\n"
            "        with self._b_lock:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "lock-order" in _rules(report)

    def test_self_reacquire_goes_red(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            with self._lock:\n"
            "                pass\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "lock-order" in _rules(report)

    def test_consistent_global_order_is_clean(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "    def f(self, other):\n"
            "        with self._a:\n"
            "            with other.stats_lock:\n"
            "                pass\n"
            "    def g(self, other):\n"
            "        with self._a:\n"
            "            with other.stats_lock:\n"
            "                pass\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "lock-order" not in _rules(report)

    def test_same_attr_in_different_classes_is_not_a_cycle(self):
        # `self._lock` in two unrelated classes must not alias.
        src = (
            "import threading\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.peer_lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            with self.peer_lock:\n"
            "                pass\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.peer_lock = threading.Lock()\n"
            "    def g(self):\n"
            "        with self.peer_lock:\n"
            "            with self._lock:\n"
            "                pass\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "lock-order" not in _rules(report)


class TestTraceStageRegistry:
    def test_unregistered_literal_goes_red(self):
        src = (
            "from ..obs import trace as _obs\n"
            "def f(t0, t1):\n"
            "    _obs.record('device_vrfy', t0, t1)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "trace-stage-registry" in _rules(report)

    def test_registered_names_and_flow_prefix_are_clean(self):
        src = (
            "from ..obs import trace as _obs\n"
            "def f(t0, t1, name):\n"
            "    _obs.record('device_verify', t0, t1)\n"
            "    _obs.record('raft_commit', t0, t1)\n"
            "    _obs.record(f'flow:{name}', t0, t1)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "trace-stage-registry" not in _rules(report)

    def test_unregistered_dynamic_prefix_goes_red(self):
        src = (
            "from ..obs import trace as _obs\n"
            "def f(t0, t1, name):\n"
            "    _obs.record(f'stage:{name}', t0, t1)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "trace-stage-registry" in _rules(report)

    def test_variable_names_and_obs_internal_sites_are_skipped(self):
        src = (
            "from ..obs import trace as _obs\n"
            "def f(t0, t1, name):\n"
            "    _obs.record(name, t0, t1)\n"
        )
        assert "trace-stage-registry" not in _rules(
            analyze_source(src, "corda_tpu/node/x.py"))
        red = "from . import trace as _obs\ndef f():\n    _obs.record('x', 0, 1)\n"
        assert "trace-stage-registry" not in _rules(
            analyze_source(red, "corda_tpu/obs/collect.py"))

    def test_registry_and_breakdown_share_one_source_of_truth(self):
        from corda_tpu.obs import collect, stages

        assert collect.STAGES is stages.STAGES
        assert set(stages.BATCH_STAGES) <= set(stages.STAGES)
        assert set(stages.DIRECT_STAGES) <= set(stages.STAGES)
        assert set(stages.DERIVED_STAGES) <= set(stages.STAGES)

    # Round 16: the rule also covers telemetry metric names — a typo'd
    # inc()/observe() literal raises ValueError at runtime (possibly only
    # on a rare error path), so it must go red at lint time.

    def test_unregistered_telemetry_metric_goes_red(self):
        src = (
            "from ..obs import telemetry as _tm\n"
            "def f():\n"
            "    _tm.inc('verify_batchs_total')\n"
            "    _tm.observe('round_wall_seconds', 0.1)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert _rules(report).count("trace-stage-registry") == 1

    def test_registered_telemetry_metric_names_are_clean(self):
        src = (
            "from ..obs import telemetry as _tm\n"
            "from ..obs.telemetry import inc\n"
            "def f(n):\n"
            "    _tm.inc('verify_batches_total')\n"
            "    _tm.observe('verify_batch_sigs', n)\n"
            "    inc('rounds_total')\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "trace-stage-registry" not in _rules(report)

    def test_from_imported_inc_with_unknown_name_goes_red(self):
        src = (
            "from ..obs.telemetry import inc as _inc\n"
            "def f():\n"
            "    _inc('made_up_total')\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "trace-stage-registry" in _rules(report)

    def test_variable_metric_names_are_skipped(self):
        # Dynamic names are the runtime registry's job (inc raises on an
        # unregistered name) — the lexical rule only judges literals.
        src = (
            "from ..obs import telemetry as _tm\n"
            "def f(name):\n"
            "    _tm.inc(name)\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "trace-stage-registry" not in _rules(report)


# ---------------------------------------------------------------------------
# Suppression + baseline machinery
# ---------------------------------------------------------------------------


class TestSuppressions:
    def test_allow_without_reason_is_itself_a_finding(self):
        src = (
            "def f(g):\n"
            "    try:\n"
            "        g()\n"
            "    # lint: allow(no-silent-except)\n"
            "    except Exception:\n"
            "        pass\n"
        )
        report = analyze_source(src, "corda_tpu/node/x.py")
        rules = _rules(report)
        assert "bad-suppression" in rules
        assert "no-silent-except" in rules  # the reasonless allow is void

    def test_allow_naming_unknown_rule_is_a_finding(self):
        src = "# lint: allow(no-such-rule) because reasons\nx = 1\n"
        report = analyze_source(src, "corda_tpu/node/x.py")
        assert "bad-suppression" in _rules(report)

    def test_trailing_allow_on_same_line_works(self):
        src = (
            "import time as _time\n"
            "def f():\n"
            "    return _time.time()  # lint: allow(no-wallclock-in-apply) coordinator stamp\n"
        )
        report = analyze_source(src, RAFT_PATH)
        assert "no-wallclock-in-apply" not in _rules(report)
        assert len(report.suppressed) == 1


class TestBaseline:
    def test_round_trip(self):
        src = TestNoSilentExcept.VIOLATION
        first = analyze_source(src, "corda_tpu/node/x.py")
        entries = baseline_entries_from_findings(first.findings,
                                                 "accepted pre-existing")
        second = analyze_source(src, "corda_tpu/node/x.py",
                                baseline_entries=entries)
        assert second.clean
        assert len(second.baselined) == len(first.findings)

    def test_entry_for_missing_file_goes_stale(self):
        entries = [{"rule": "no-silent-except",
                    "path": "corda_tpu/node/deleted.py",
                    "code": "except Exception:", "count": 1,
                    "reason": "was accepted"}]
        report = analyze_source("x = 1\n", "corda_tpu/node/x.py",
                                baseline_entries=entries)
        assert "stale-baseline" in _rules(report)

    def test_unmatched_and_reasonless_entries_go_stale(self):
        entries = [
            {"rule": "no-silent-except", "path": "corda_tpu/node/x.py",
             "code": "except Exception:", "count": 1, "reason": "fixed?"},
            {"rule": "no-silent-except", "path": "corda_tpu/node/x.py",
             "code": "except BaseException:", "count": 1, "reason": ""},
        ]
        report = analyze_source("x = 1\n", "corda_tpu/node/x.py",
                                baseline_entries=entries)
        assert _rules(report).count("stale-baseline") == 2

    def test_budget_absorbs_count_then_surfaces_excess(self):
        src = TestNoSilentExcept.VIOLATION * 2  # two identical sites
        entries = [{"rule": "no-silent-except", "path": "corda_tpu/node/x.py",
                    "code": "except Exception:", "count": 1,
                    "reason": "only one accepted"}]
        report = analyze_source(src, "corda_tpu/node/x.py",
                                baseline_entries=entries)
        assert _rules(report).count("no-silent-except") == 1
        assert len(report.baselined) == 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class TestCli:
    def test_json_mode_and_exit_codes(self, tmp_path, capsys):
        bad = tmp_path / "corda_tpu" / "node" / "x.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(TestNoSilentExcept.VIOLATION)
        rc = cli_main(["--json", "--no-baseline", str(bad)])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert doc["clean"] is False
        assert doc["findings"][0]["rule"] == "no-silent-except"
        assert doc["findings"][0]["line"] == 4

        good = tmp_path / "corda_tpu" / "node" / "y.py"
        good.write_text("x = 1\n")
        rc = cli_main(["--json", "--no-baseline", str(good)])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["clean"] is True

    def test_list_rules_names_all_six(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ALL_RULES:
            assert rule.name in out
        assert len(ALL_RULES) >= 6

    def test_bench_report_stamp_is_zero(self):
        # What bench.py embeds in the report header: live findings on the
        # shipped tree via the checked-in baseline.
        report = analyze_paths([TREE])
        assert len(report.findings) == 0
