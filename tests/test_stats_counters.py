"""Cross-thread stats-counter regressions (invariant-analyzer sweep).

Three counters were bumped with unguarded read-modify-write from threads
other than their reader:

  * TcpMessaging._flush_stats / _stale_resends — bumped on every bridge
    thread, read by transport_stats() on the node/bench thread;
  * SidecarServer.requests — bumped on per-connection reader threads under
    the WRONG lock (_cv) while stats() reads under _lock.

The hammer tests below drive the fixed bump paths from many threads with a
tiny GIL switch interval (which reliably loses updates on the old code) and
assert EXACT totals. The AST guards pin the structural fix so a refactor
can't quietly move a bump back outside its lock.
"""

import ast
import sys
import threading
from pathlib import Path

import pytest

from corda_tpu.crypto.sidecar import SidecarServer
from corda_tpu.node.messaging.tcp import TcpMessaging

REPO = Path(__file__).resolve().parents[1]

THREADS = 8
PER_THREAD = 2_000


@pytest.fixture
def tiny_switch_interval():
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        yield
    finally:
        sys.setswitchinterval(old)


def _hammer(fn):
    threads = [threading.Thread(target=fn) for _ in range(THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class TestTcpBridgeCounters:
    def test_concurrent_note_flush_loses_no_updates(self, tiny_switch_interval):
        messaging = TcpMessaging()  # not started: no sockets, just state

        def bump():
            for _ in range(PER_THREAD):
                messaging._note_flush(3)

        _hammer(bump)
        stats = messaging.transport_stats()
        assert stats["bridge_flushes"] == THREADS * PER_THREAD
        assert stats["bridge_flush_frames"] == THREADS * PER_THREAD * 3
        assert stats["bridge_max_flush"] == 3

    def test_concurrent_stale_resends_lose_no_updates(self, tiny_switch_interval):
        messaging = TcpMessaging()

        def bump():
            for _ in range(PER_THREAD):
                messaging._note_stale_resend()

        _hammer(bump)
        assert messaging.transport_stats()["stale_resends"] == \
            THREADS * PER_THREAD

    def test_reads_race_writes_without_tearing(self, tiny_switch_interval):
        messaging = TcpMessaging()
        stop = threading.Event()
        seen = []

        def read():
            while not stop.is_set():
                st = messaging.transport_stats()
                # frames is always exactly 3x flushes: a torn read of the
                # dict mid-update would break the ratio.
                assert st["bridge_flush_frames"] == 3 * st["bridge_flushes"]
                seen.append(st["bridge_flushes"])

        reader = threading.Thread(target=read)
        reader.start()
        _hammer(lambda: [messaging._note_flush(3)
                         for _ in range(PER_THREAD)])
        stop.set()
        reader.join()
        assert messaging.transport_stats()["bridge_flushes"] == \
            THREADS * PER_THREAD

    def test_flush_stats_only_mutate_inside_guarded_helper(self):
        """AST guard: every _flush_stats/_stale_resends mutation lives in
        the _note_* helpers (whose bodies hold _stats_lock) — a new bump
        site outside them reintroduces the race this file regression-tests."""
        tree = ast.parse(
            (REPO / "corda_tpu/node/messaging/tcp.py").read_text())
        offenders = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in ("_note_flush", "_note_stale_resend",
                             "__init__"):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.AugAssign):
                    targets = [sub.target]
                elif isinstance(sub, ast.Assign):
                    targets = sub.targets
                else:
                    continue
                tgt = " ".join(ast.unparse(t) for t in targets)
                # Mutating the counters, or aliasing the live dict (the
                # old `st = self._flush_stats; st[...] += 1` pattern) —
                # copies like dict(self._flush_stats) stay legal.
                aliasing = (isinstance(sub, ast.Assign) and
                            ast.unparse(sub.value) == "self._flush_stats")
                if "_flush_stats" in tgt or "_stale_resends" in tgt \
                        or aliasing:
                    offenders.append(
                        f"{node.name}:{sub.lineno}: {ast.unparse(sub)}")
        assert not offenders, offenders


class TestSidecarRequestCounter:
    def _server(self):
        # verifier stub: the counter paths never dispatch
        return SidecarServer("127.0.0.1:0", verifier=object())

    def test_concurrent_request_bumps_lose_no_updates(
            self, tiny_switch_interval):
        server = self._server()

        def bump():
            # the fixed _serve_conn pattern: stats counters under _lock
            for _ in range(PER_THREAD):
                with server._lock:
                    server.requests += 1

        _hammer(bump)
        assert server.requests == THREADS * PER_THREAD

    def test_request_bump_sits_under_stats_lock_not_cv(self):
        """AST guard: the `requests += 1` in _serve_conn must be inside a
        `with self._lock` block (the lock stats() reads under), never back
        under self._cv where stats-lock writers can race it."""
        tree = ast.parse(
            (REPO / "corda_tpu/crypto/sidecar.py").read_text())
        checked = 0
        for node in ast.walk(tree):
            if not isinstance(node, ast.With):
                continue
            locks = [ast.unparse(item.context_expr) for item in node.items]
            for sub in ast.walk(node):
                if isinstance(sub, ast.AugAssign) and \
                        ast.unparse(sub.target) == "self.requests":
                    assert locks == ["self._lock"], (
                        f"requests bump at line {sub.lineno} under {locks}")
                    checked += 1
        assert checked == 1


class TestStateMachineHandlerRemoveMetric:
    def _manager(self, remove_exc):
        from corda_tpu.node.statemachine import StateMachineManager

        class _Messaging:
            def remove_message_handler(self, registration):
                raise remove_exc

        class _Checkpoints:
            def remove_checkpoint(self, run_id):
                pass

        class _Changes:
            def append(self, item):
                pass

        smm = object.__new__(StateMachineManager)
        smm.flows = {}
        smm._dirty_checkpoints = {}
        smm.checkpoint_storage = _Checkpoints()
        smm.metrics = {"finished": 0, "handler_remove_failures": 0}
        smm._record_flow_timing = lambda fsm: None
        smm.recent_results = {}
        smm.changes = _Changes()
        smm._sessions_by_local_id = {}
        smm._session_handlers = {7: object()}
        smm.messaging = _Messaging()
        return smm

    def _fsm(self):
        class _Session:
            local_id = 7
            state = "closed"
            peer_id = None
            party = None

        class _Fsm:
            run_id = b"run"
            future = object()
            sessions = {7: _Session()}

        return _Fsm()

    def test_teardown_race_is_counted_not_swallowed(self):
        smm = self._manager(KeyError("already removed"))
        smm._flow_finished(self._fsm())
        assert smm.metrics["handler_remove_failures"] == 1

    def test_unexpected_failures_now_propagate(self):
        # The old `except Exception: pass` swallowed everything; the
        # narrowed handler lets genuinely unexpected faults surface.
        smm = self._manager(RuntimeError("broken messaging"))
        with pytest.raises(RuntimeError):
            smm._flow_finished(self._fsm())
