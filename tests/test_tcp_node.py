"""Real nodes over real localhost TCP sockets + sqlite — the production tier.

Mirrors the reference's integration tier (reference: node/src/integration-test,
driver DSL at node/.../driver/Driver.kt:56-107) in-process: each Node owns its
own sqlite file and TCP listener; the test round-robins run_once() as the
scheduler, so delivery order is still deterministic enough to assert on.

Covers VERDICT round-1 items 4 (durable node with new-process semantics) and
5 (real transport: durable outbox, retry, dedupe, 2-node + notary smoke).
"""

import time

import pytest

from corda_tpu.flows.notary import NotaryClientFlow, NotaryException
from corda_tpu.node.config import BatchConfig, NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.testing.dummies import DummyContract


def make_node(tmp_path, name, notary="none", netmap="netmap.json", **kw):
    config = NodeConfig(
        name=name,
        base_dir=tmp_path / name,
        port=0,
        notary=notary,
        network_map=tmp_path / netmap,
        batch=BatchConfig(max_sigs=kw.pop("max_sigs", 4096),
                          max_wait_ms=kw.pop("max_wait_ms", 2.0)),
        **kw,
    )
    return Node(config).start()


def pump_until(nodes, predicate, timeout=15.0):
    """Round-robin run_once across nodes until predicate() or timeout.

    Netmap refresh is throttled (as in production run_forever): re-reading
    the file every iteration made each pump cycle slow enough to quantize
    raft election timeouts to cycle boundaries — repeated split votes."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for node in nodes:
            node.run_once(timeout=0.01)
            node.refresh_netmap_maybe(every=0.2)
        if predicate():
            return
    raise AssertionError("timed out waiting for network to settle")


def issue_and_move(alice, notary_identity, magic=1):
    builder = DummyContract.generate_initial(
        alice.identity.ref(b"\x01"), magic, notary_identity)
    builder.sign_with(alice.key)
    issue_stx = builder.to_signed_transaction()
    alice.services.record_transactions([issue_stx])
    move = DummyContract.move(issue_stx.tx.out_ref(0),
                              alice.identity.owning_key)
    move.sign_with(alice.key)
    return move.to_signed_transaction(check_sufficient_signatures=False)


class TestTcpNotarisation:
    def test_two_nodes_plus_notary_smoke(self, tmp_path):
        notary = make_node(tmp_path, "Notary", notary="simple")
        alice = make_node(tmp_path, "Alice")
        bob = make_node(tmp_path, "Bob")
        nodes = [notary, alice, bob]
        try:
            for n in nodes:
                n.refresh_netmap()
            stx = issue_and_move(alice, notary.identity)
            handle = alice.start_flow(NotaryClientFlow(stx))
            pump_until(nodes, lambda: handle.result.done)
            sig = handle.result.result()
            assert sig.by in notary.identity.owning_key.keys
            sig.verify(stx.id.bytes)
            assert notary.uniqueness_provider.committed_count == 1
        finally:
            for n in nodes:
                n.stop()

    def test_double_spend_rejected_across_tcp(self, tmp_path):
        notary = make_node(tmp_path, "Notary", notary="simple")
        alice = make_node(tmp_path, "Alice")
        nodes = [notary, alice]
        try:
            for n in nodes:
                n.refresh_netmap()
            builder = DummyContract.generate_initial(
                alice.identity.ref(b"\x01"), 5, notary.identity)
            builder.sign_with(alice.key)
            issue_stx = builder.to_signed_transaction()
            alice.services.record_transactions([issue_stx])
            prior = issue_stx.tx.out_ref(0)

            m1 = DummyContract.move(prior, alice.identity.owning_key)
            m1.sign_with(alice.key)
            stx1 = m1.to_signed_transaction(check_sufficient_signatures=False)
            m2 = DummyContract.move(prior, notary.identity.owning_key)
            m2.sign_with(alice.key)
            stx2 = m2.to_signed_transaction(check_sufficient_signatures=False)
            assert stx1.id != stx2.id

            h1 = alice.start_flow(NotaryClientFlow(stx1))
            pump_until(nodes, lambda: h1.result.done)
            h1.result.result()

            h2 = alice.start_flow(NotaryClientFlow(stx2))
            pump_until(nodes, lambda: h2.result.done)
            with pytest.raises(NotaryException) as err:
                h2.result.result()
            assert "used in another transaction" in str(err.value)
        finally:
            for n in nodes:
                n.stop()

def test_notary_restart_new_process_semantics(tmp_path):
    """Kill the notary node (drop every object), rebuild purely from its
    base_dir, and verify (a) the commit log survived sqlite-durably and (b) a
    notarisation started while it was down completes after rebirth (durable
    outbox + bridge retry — store-and-forward across a peer restart)."""
    notary = make_node(tmp_path, "Notary", notary="simple")
    alice = make_node(tmp_path, "Alice")
    survivors = [alice]
    try:
        for n in (notary, alice):
            n.refresh_netmap()
        stx = issue_and_move(alice, notary.identity, magic=9)
        h = alice.start_flow(NotaryClientFlow(stx))
        pump_until([notary, alice], lambda: h.result.done)
        h.result.result()
        assert notary.uniqueness_provider.committed_count == 1
        notary_config = notary.config
        notary_identity = notary.identity

        # -- crash: drop every in-memory object -----------------------------
        notary.stop()
        del notary
        time.sleep(0.05)

        # While down, Alice fires a second notarisation; the send parks in
        # her durable outbox and the bridge retries.
        stx2 = issue_and_move(alice, notary_identity, magic=10)
        h2 = alice.start_flow(NotaryClientFlow(stx2))
        for _ in range(5):
            alice.run_once(timeout=0.01)
        assert not h2.result.done  # notary is down; flow is parked

        # -- rebirth purely from the base_dir (fresh port; netmap updates) --
        reborn = Node(NodeConfig(
            name=notary_config.name,
            base_dir=notary_config.base_dir,
            port=0,
            notary="simple",
            network_map=notary_config.network_map,
        )).start()
        survivors.append(reborn)
        assert reborn.identity == notary_identity  # key survived on disk
        assert reborn.uniqueness_provider.committed_count == 1  # log survived

        pump_until([alice, reborn], lambda: h2.result.done)
        sig2 = h2.result.result()
        sig2.verify(stx2.id.bytes)
        assert reborn.uniqueness_provider.committed_count == 2
    finally:
        for n in survivors:
            n.stop()


class TestKillAtStepSqlite:
    """Kill-at-every-step re-run against the DURABLE stack: sqlite checkpoint
    storage + TCP transport, with rebirth strictly from the base_dir (no
    object hand-over — the new-process semantics VERDICT r1 asked for)."""

    @pytest.mark.parametrize("crash_after", [1, 2, 3])
    @pytest.mark.parametrize("victim", ["client", "notary"])
    def test_crash_at_step(self, tmp_path, crash_after, victim):
        notary = make_node(tmp_path, "Notary", notary="simple")
        alice = make_node(tmp_path, "Alice")
        nodes = {"notary": notary, "client": alice}
        try:
            for n in nodes.values():
                n.refresh_netmap()
            stx = issue_and_move(alice, notary.identity, magic=crash_after)
            alice.start_flow(NotaryClientFlow(stx))

            dispatched = 0
            crashed = False
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                for node in list(nodes.values()):
                    dispatched += node.run_once(timeout=0.01)
                if not crashed and dispatched >= crash_after:
                    crashed = True
                    dead = nodes[victim]
                    config = dead.config
                    dead.stop()
                    del dead, nodes[victim]
                    # Rebirth purely from disk.
                    nodes[victim] = Node(NodeConfig(
                        name=config.name,
                        base_dir=config.base_dir,
                        port=0,
                        notary=config.notary,
                        network_map=config.network_map,
                    )).start()
                if nodes["notary"].uniqueness_provider.committed_count == 1 \
                        and not any(n.smm.flows for n in nodes.values()):
                    break
            assert crashed, "network settled before the crash point"
            assert nodes["notary"].uniqueness_provider.committed_count == 1, (
                f"crash_after={crash_after} victim={victim}: "
                "protocol did not complete")
        finally:
            for n in nodes.values():
                n.stop()


class TestTlsTransport:
    def test_notarisation_over_mutual_tls(self, tmp_path):
        """TLS-enabled nodes (certs chained to the shared dev CA) complete a
        notarisation; a plaintext client cannot talk to a TLS node."""
        pytest.importorskip(
            "cryptography",
            reason="the 'cryptography' wheel is not installed — TLS "
                   "material generation (crypto/x509.py) requires it")
        notary = make_node(tmp_path, "Notary", notary="simple", tls=True)
        alice = make_node(tmp_path, "Alice", tls=True)
        nodes = [notary, alice]
        try:
            for n in nodes:
                n.refresh_netmap()
            assert (tmp_path / "dev-ca.pem").exists()
            assert (tmp_path / "Alice" / "certificates" / "tls-cert.pem").exists()
            stx = issue_and_move(alice, notary.identity, magic=21)
            h = alice.start_flow(NotaryClientFlow(stx))
            pump_until(nodes, lambda: h.result.done)
            h.result.result().verify(stx.id.bytes)

            # A plaintext endpoint is refused by the TLS listener: its sends
            # never ack (handshake bytes are not a valid frame).
            from corda_tpu.node.messaging.api import TopicSession
            from corda_tpu.node.messaging.tcp import TcpMessaging

            plain = TcpMessaging("127.0.0.1", 0).start()
            plain.send(TopicSession("platform.session", 0), b"junk",
                       notary.messaging.my_address)
            import time as _t

            before = notary.smm.metrics["started"]
            deadline = _t.monotonic() + 1.5
            while _t.monotonic() < deadline:
                for n in nodes:
                    n.run_once(timeout=0.01)
            assert notary.smm.metrics["started"] == before
            plain.stop()
        finally:
            for n in nodes:
                n.stop()


class TestHostileSocket:
    @pytest.mark.filterwarnings(
        "error::pytest.PytestUnhandledThreadExceptionWarning")
    def test_raw_garbage_on_the_wire_does_not_kill_the_node(self, tmp_path):
        """A port-scanner / hostile client writing raw bytes (bad framing,
        oversized length prefixes, empty connects) must not take the node
        down or wedge its accept loop — legitimate traffic keeps flowing."""
        import socket
        import struct

        notary = make_node(tmp_path, "Notary", notary="simple")
        alice = make_node(tmp_path, "Alice")
        nodes = [notary, alice]
        try:
            for n in nodes:
                n.refresh_netmap()
            addr = (notary.messaging.my_address.host,
                    notary.messaging.my_address.port)
            payloads = [
                b"",                                   # connect + close
                b"\x00",                               # short read
                b"GET / HTTP/1.1\r\n\r\n",             # wrong protocol
                struct.pack(">I", 0xFFFFFFF0) + b"x",  # absurd length prefix
                b"\xff" * 4096,                        # framed-looking noise
            ]
            # a WELL-FRAMED frame whose payload decodes to a non-sequence
            from corda_tpu.serialization.codec import serialize

            scalar = bytes(serialize(7).bytes)
            payloads.append(struct.pack(">I", len(scalar)) + scalar)
            # a well-formed 'msg' frame with WRONG-TYPED fields (dict where
            # the dedupe id must be bytes) must die at the reader, not on
            # the node's pump thread
            evil = bytes(serialize(
                ("msg", "platform.session", 0, {"a": 1}, "h", 1, b"")).bytes)
            payloads.append(struct.pack(">I", len(evil)) + evil)
            for payload in payloads:
                s = socket.create_connection(addr, timeout=2)
                try:
                    if payload:
                        s.sendall(payload)
                finally:
                    s.close()
                for n in nodes:
                    n.run_once(timeout=0.01)
            # the node still serves legitimate protocol traffic
            stx = issue_and_move(alice, notary.identity, magic=77)
            h = alice.start_flow(NotaryClientFlow(stx))
            pump_until(nodes, lambda: h.result.done)
            h.result.result().verify(stx.id.bytes)
        finally:
            for n in nodes:
                n.stop()


    @pytest.mark.filterwarnings(
        "error::pytest.PytestUnhandledThreadExceptionWarning")
    def test_garbage_acking_peer_does_not_kill_the_bridge(self, tmp_path):
        """An outbound bridge whose peer replies with garbage instead of
        ACK frames must reconnect-and-retry, not lose its thread — and the
        node keeps serving other peers."""
        import socket
        import threading

        from corda_tpu.node.messaging.api import TopicSession
        from corda_tpu.node.messaging.tcp import TcpAddress

        notary = make_node(tmp_path, "Notary", notary="simple")
        alice = make_node(tmp_path, "Alice")
        nodes = [notary, alice]

        fake = socket.socket()
        fake.bind(("127.0.0.1", 0))
        fake.listen(4)
        fake_addr = TcpAddress("127.0.0.1", fake.getsockname()[1])
        hits = []

        def fake_peer():
            fake.settimeout(5)
            try:
                while len(hits) < 2:  # original connect + >=1 reconnect
                    conn, _ = fake.accept()
                    hits.append(1)
                    conn.settimeout(2)
                    try:
                        conn.recv(4096)  # the bridged frame
                        conn.sendall(b"\xde\xad\xbe\xef" * 4)  # garbage
                    except OSError:
                        pass
                    conn.close()
            except OSError:
                pass

        t = threading.Thread(target=fake_peer, daemon=True)
        t.start()
        try:
            for n in nodes:
                n.refresh_netmap()
            alice.messaging.send(TopicSession("platform.session", 0),
                                 b"payload", fake_addr)
            deadline = __import__("time").monotonic() + 6
            while __import__("time").monotonic() < deadline and len(hits) < 2:
                for n in nodes:
                    n.run_once(timeout=0.01)
            assert len(hits) >= 2, "bridge never reconnected after garbage"
            # the node still serves legitimate peers
            stx = issue_and_move(alice, notary.identity, magic=88)
            h = alice.start_flow(NotaryClientFlow(stx))
            pump_until(nodes, lambda: h.result.done)
            h.result.result().verify(stx.id.bytes)
        finally:
            fake.close()
            t.join(timeout=2)
            for n in nodes:
                n.stop()


class TestRoundTransactions:
    """The run-loop round batch (NodeDatabase.batch + TcpMessaging round
    deferral): a round commits as ONE unit, a failed round rolls back as one
    unit, and the dedupe/ACK machinery follows the transaction's fate."""

    def test_round_commits_as_unit(self, tmp_path):
        from corda_tpu.node.services.persistence import NodeDatabase

        db = NodeDatabase(tmp_path / "n.db")
        with db.batch():
            db.set_setting("a", "1")
            db.set_setting("b", "2")
            # Not yet visible to a second connection (uncommitted).
            assert db.aux_conn.execute(
                "SELECT COUNT(*) FROM settings WHERE key IN ('a','b')"
            ).fetchone()[0] == 0
        assert db.get_setting("a") == "1"
        assert db.get_setting("b") == "2"
        db.close()

    def test_failed_round_rolls_back(self, tmp_path):
        from corda_tpu.node.services.persistence import NodeDatabase

        db = NodeDatabase(tmp_path / "n.db")
        with pytest.raises(RuntimeError):
            with db.batch():
                db.set_setting("a", "1")
                raise RuntimeError("mid-round failure")
        assert db.get_setting("a") is None
        # The connection stays usable for the next round.
        with db.batch():
            db.set_setting("a", "2")
        assert db.get_setting("a") == "2"
        db.close()

    def test_foreign_thread_commit_is_immediate(self, tmp_path):
        # A webserver-style thread must keep commit-before-return while the
        # node thread holds a round open (db.lock serializes them).
        import threading

        from corda_tpu.node.services.persistence import (
            DBAttachmentStorage,
            NodeDatabase,
        )

        db = NodeDatabase(tmp_path / "n.db")
        storage = DBAttachmentStorage(db)
        in_round = threading.Event()
        release = threading.Event()
        result = {}

        def node_round():
            with db.batch():
                db.set_setting("round", "open")
                in_round.set()
                release.wait(timeout=5.0)

        def http_upload():
            in_round.wait(timeout=5.0)
            att_id = storage.import_attachment(b"payload")
            # By the time import_attachment returns, the row must be durable
            # (visible to an independent connection).
            result["count"] = db.aux_conn.execute(
                "SELECT COUNT(*) FROM attachments WHERE att_id = ?",
                (att_id.bytes,)).fetchone()[0]

        t1 = threading.Thread(target=node_round)
        t2 = threading.Thread(target=http_upload)
        t1.start()
        t2.start()
        # The upload blocks on db.lock until the round ends.
        release.set()
        t1.join(timeout=10.0)
        t2.join(timeout=10.0)
        assert result.get("count") == 1
        db.close()

    def test_dedupe_mirror_follows_round_fate(self, tmp_path):
        from corda_tpu.node.messaging.tcp import _Dedupe
        from corda_tpu.node.services.persistence import NodeDatabase

        db = NodeDatabase(tmp_path / "n.db")
        dedupe = _Dedupe(db)
        # Aborted round: the mirror entry must unwind with the rollback so a
        # redelivery is processed, not swallowed.
        try:
            with db.batch():
                dedupe.record(b"lost-message")
                raise RuntimeError("round failed")
        except RuntimeError:
            pass
        dedupe.round_aborted()
        assert not dedupe.seen(b"lost-message")
        # Committed round: the entry stays.
        with db.batch():
            dedupe.record(b"kept-message")
        dedupe.round_committed()
        assert dedupe.seen(b"kept-message")
        db.close()

    def test_flush_checkpoints_fails_bad_flow_in_place(self):
        # Round-3 advisor: propagating a serialization error out of
        # flush_checkpoints rolled back the WHOLE round and exited the node;
        # restart replayed the flow to the same unserializable state — a
        # permanent crash loop. The bad flow must instead be failed like a
        # handler exception would fail it, keeping the round committable.
        from corda_tpu.flows.api import FlowException
        from corda_tpu.node.statemachine import (
            InMemoryCheckpointStorage,
            StateMachineManager,
        )

        failed = []

        class _Good:
            state = "runnable"
            run_id = b"good"

        class _Bad:
            state = "runnable"
            run_id = b"bad"

            def _fail(self, exc):
                self.state = "done"
                failed.append(exc)

        storage = InMemoryCheckpointStorage()
        smm = StateMachineManager.__new__(StateMachineManager)
        smm.defer_checkpoints = True
        smm.checkpoint_storage = storage
        smm.metrics = {"checkpointing_rate": 0}

        def ser(fsm):
            if fsm.run_id == b"bad":
                raise FlowException("unserializable flow state")
            return b"blob-" + fsm.run_id

        smm._serialize_checkpoint = ser
        smm._dirty_checkpoints = {b"bad": _Bad(), b"good": _Good()}
        assert smm.flush_checkpoints() == 1
        assert [type(e) for e in failed] == [FlowException]
        # The good flow's checkpoint was written; the dirty set is drained.
        assert list(storage.checkpoints()) == [b"blob-good"]
        assert smm._dirty_checkpoints == {}

    def test_flush_checkpoints_storage_error_still_aborts_round(self):
        # A STORAGE write failure compromises every flow's durability, not
        # one flow's state: it must propagate and abort the round.
        from corda_tpu.node.statemachine import StateMachineManager

        class _Flow:
            state = "runnable"
            run_id = b"f"

        class _BrokenStorage:
            def update_checkpoint(self, run_id, blob):
                raise OSError("disk full")

        smm = StateMachineManager.__new__(StateMachineManager)
        smm.defer_checkpoints = True
        smm.checkpoint_storage = _BrokenStorage()
        smm.metrics = {"checkpointing_rate": 0}
        smm._serialize_checkpoint = lambda fsm: b"blob"
        smm._dirty_checkpoints = {b"f": _Flow()}
        with pytest.raises(OSError):
            smm.flush_checkpoints()
