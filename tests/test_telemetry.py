"""Always-on telemetry plane (corda_tpu/obs/telemetry.py + export.py).

Covers the ISSUE acceptance list: the pre-interned metric registry (an
unregistered name raises instead of silently vanishing), power-of-two
histogram bucket math, the Prometheus text endpoint serving EVERY
registered metric in valid exposition form (node webserver GET /metrics
and the sidecar's OP_METRICS frame), exact cross-process snapshot
merging, the round profiler attributing >= 90% of live round wall time,
and the flight recorder's exactly-one-artifact-per-reason latch across
its trigger matrix (manual/SLO-breach, overload spike, crash).
"""

import json
import os
import shutil
import tempfile
import urllib.request

import pytest

from corda_tpu.crypto.provider import CpuVerifier, VerifyJob
from corda_tpu.crypto.sidecar import SidecarServer
from corda_tpu.node.config import NodeConfig
from corda_tpu.node.node import Node
from corda_tpu.node.verify_client import SidecarVerifier
from corda_tpu.obs import telemetry as tm
from corda_tpu.obs.export import (CONTENT_TYPE, PREFIX, collect_cluster,
                                  fetch_sidecar_metrics, merge_snapshots,
                                  parse_prometheus, render_prometheus)


@pytest.fixture()
def fresh():
    """A fresh registry for isolation; leaves a fresh one armed after
    (always-on is the module's default state, tests must restore it)."""
    reg = tm.arm()
    yield reg
    tm.arm()


# ---------------------------------------------------------------------------
# Registry: pre-interned names, rejection, disarmed cost
# ---------------------------------------------------------------------------


def test_registry_preinterns_every_registered_name(fresh):
    assert set(fresh.counters) == set(tm.COUNTER_NAMES)
    assert set(fresh.histograms) == set(tm.HISTOGRAM_NAMES)
    assert tm.METRIC_NAMES == (set(tm.COUNTER_NAMES)
                               | set(tm.HISTOGRAM_NAMES))


def test_unregistered_names_raise(fresh):
    with pytest.raises(ValueError, match="not registered"):
        fresh.counter("made_up_total")
    with pytest.raises(ValueError, match="not registered"):
        fresh.histogram("made_up_seconds")
    with pytest.raises(ValueError):
        tm.inc("made_up_total")
    with pytest.raises(ValueError):
        tm.observe("made_up_seconds", 0.1)


def test_helpers_update_the_active_registry(fresh):
    tm.inc("rounds_total")
    tm.inc("verify_sigs_total", 5)
    tm.observe("verify_batch_sigs", 5)
    snap = tm.snapshot()
    assert snap["counters"]["rounds_total"] == 1
    assert snap["counters"]["verify_sigs_total"] == 5
    assert snap["histograms"]["verify_batch_sigs"]["count"] == 1


def test_disarmed_path_is_a_noop_even_for_bad_names():
    # The hot-path guard is the attribute check — while disarmed nothing
    # validates, allocates, or raises (the one-attribute-check cost bound).
    tm.disarm()
    try:
        tm.inc("not_even_registered")
        tm.observe("also_not_registered", 1.0)
        tm.observe_round(0.01, {"poll": 0.01})
        assert tm.snapshot() is None
        assert tm.flight_trigger("crash") is None
    finally:
        tm.arm()


def test_observe_round_fans_into_phase_counters(fresh):
    tm.observe_round(0.010, {"poll": 0.006, "verify_wait": 0.002,
                             "apply": 0.001, "reply": 0.001})
    c = tm.snapshot()["counters"]
    assert c["rounds_total"] == 1
    assert c["round_wall_seconds_total"] == pytest.approx(0.010)
    assert c["round_phase_poll_seconds_total"] == pytest.approx(0.006)
    # Unnamed phases observe 0 — every phase histogram stays in lockstep.
    assert tm.snapshot()["histograms"][
        "round_phase_seal_seconds"]["count"] == 1


# ---------------------------------------------------------------------------
# Histogram bucket math
# ---------------------------------------------------------------------------


def test_power_of_two_buckets_for_counts():
    h = tm.Histogram("verify_batch_sigs")
    assert h.scale == 1
    for v in (1, 3, 4, 100):
        h.observe(v)
    # bucket i holds values with int(v).bit_length() == i.
    assert h.buckets == {1: 1, 2: 1, 3: 1, 7: 1}
    assert h.count == 4 and h.sum == 108
    assert h.bucket_upper(7) == 128


def test_seconds_histograms_scale_to_microseconds():
    h = tm.Histogram("round_wall_seconds")
    assert h.scale == 1_000_000
    h.observe(0.001)  # 1000 us -> bit_length 10
    assert h.buckets == {10: 1}
    assert h.bucket_upper(10) == pytest.approx(1024 / 1e6)


def test_huge_values_clamp_into_the_top_bucket():
    h = tm.Histogram("round_wall_seconds")
    h.observe(1e30)
    assert h.buckets == {63: 1}


def test_quantile_overestimates_by_at_most_one_bucket():
    h = tm.Histogram("verify_batch_sigs")
    for v in (10, 10, 10, 1000):
        h.observe(v)
    assert h.quantile(0.5) == 16       # 10 lives in (8, 16]
    assert h.quantile(1.0) == 1024
    assert tm.Histogram("verify_batch_sigs").quantile(0.5) is None


# ---------------------------------------------------------------------------
# format_breakdown
# ---------------------------------------------------------------------------


def test_format_breakdown_shares_coverage_busiest():
    rp = {"poll": 0.6, "verify_wait": 0.2, "seal": 0.0, "replicate": 0.05,
          "apply": 0.05, "reply": 0.05, "wall": 1.0, "rounds": 10}
    bd = tm.format_breakdown(rp)
    assert bd["rounds"] == 10 and bd["wall_s"] == 1.0
    assert bd["phases"]["poll"]["share"] == pytest.approx(0.6)
    assert bd["coverage"] == pytest.approx(0.95)
    assert bd["busiest_phase"] == "poll"


def test_format_breakdown_abstains_without_rounds():
    assert tm.format_breakdown(None) is None
    assert tm.format_breakdown({}) is None
    assert tm.format_breakdown({"rounds": 0, "wall": 0.0}) is None


def test_loadtest_busiest_stage_is_guarded():
    from corda_tpu.tools.loadtest import (BUSIEST_STAGE_MIN_ROUNDS,
                                          _busiest_stage)

    few = {"pump": 9.0, "fsync": 1.0, "rounds": BUSIEST_STAGE_MIN_ROUNDS - 1}
    assert _busiest_stage(few) is None       # abstains under-sampled
    assert _busiest_stage(None) is None
    enough = dict(few, rounds=500)
    # "rounds" is an integer count riding in the seconds dict — it must
    # never be crowned the busiest stage.
    assert _busiest_stage(enough) == "pump"
    tied = {"verify": 2.0, "fsync": 2.0, "rounds": 100}
    assert _busiest_stage(tied) == "fsync"   # deterministic: alphabetical
    # A delta window that did no measured work abstains too — crowning
    # the alphabetical first of all-zero stages is a fabricated verdict.
    assert _busiest_stage({"pump": 0.0, "fsync": 0.0, "rounds": 100}) is None


def test_format_breakdown_overlap_rides_beside_phases_no_double_count():
    """Pipelined commit plane: executor apply time is reported in its own
    ``overlap`` block, NEVER inside ``phases`` — coverage stays a
    partition of the consensus thread's wall time, so overlap can push
    attributed work past 100% of wall without corrupting the >= 0.9
    acceptance bound."""
    rp = {"poll": 0.5, "verify_wait": 0.1, "seal": 0.1, "replicate": 0.1,
          "apply": 0.1, "reply": 0.05, "wall": 1.0, "rounds": 30,
          "overlap_apply": 0.4}
    bd = tm.format_breakdown(rp)
    assert bd["coverage"] == pytest.approx(0.95)  # six phases only
    assert "overlap_apply" not in bd["phases"]
    assert set(bd["phases"]) == set(tm.ROUND_PHASES)
    assert bd["overlap"]["apply"]["total_s"] == pytest.approx(0.4)
    assert bd["overlap"]["apply"]["vs_wall"] == pytest.approx(0.4)
    # No double count: phase totals + overlap partition DIFFERENT threads'
    # time; the in-loop phase sum alone must stay <= wall.
    phase_sum = sum(p["total_s"] for p in bd["phases"].values())
    assert phase_sum <= bd["wall_s"] + 1e-9
    # The block is absent (not zeroed) when the plane never overlapped.
    serial = {k: v for k, v in rp.items() if k != "overlap_apply"}
    assert "overlap" not in tm.format_breakdown(serial)


# ---------------------------------------------------------------------------
# Prometheus render / parse / merge
# ---------------------------------------------------------------------------


def test_render_parse_round_trip_covers_every_metric(fresh):
    tm.inc("rounds_total", 3)
    tm.inc("verify_sigs_total", 7)
    tm.observe("verify_batch_sigs", 7)
    tm.observe("round_wall_seconds", 0.004)
    text = render_prometheus()
    parsed = parse_prometheus(text)
    # Every registered metric is served, including never-fired zeros.
    assert set(parsed["counters"]) == set(tm.COUNTER_NAMES)
    assert set(parsed["histograms"]) == set(tm.HISTOGRAM_NAMES)
    snap = tm.snapshot()
    for name, v in snap["counters"].items():
        assert parsed["counters"][name] == pytest.approx(v)
    h = parsed["histograms"]["verify_batch_sigs"]
    assert h["count"] == 1 and h["sum"] == pytest.approx(7.0)
    # Cumulative buckets end at +Inf == count.
    assert h["buckets"][-1] == (float("inf"), 1)


def test_render_accepts_a_snapshot_dict(fresh):
    tm.inc("rounds_total")
    assert (render_prometheus(tm.snapshot())
            == render_prometheus(fresh))


def test_parse_rejects_malformed_expositions():
    with pytest.raises(ValueError):
        parse_prometheus(f"{PREFIX}rounds_total garbage\n")
    with pytest.raises(ValueError):
        parse_prometheus("unprefixed_metric 1\n")
    with pytest.raises(ValueError):  # histogram without +Inf
        parse_prometheus(
            f"# TYPE {PREFIX}h histogram\n"
            f'{PREFIX}h_bucket{{le="1"}} 1\n'
            f"{PREFIX}h_sum 1\n{PREFIX}h_count 1\n")


def test_merge_snapshots_is_exact(fresh):
    a, b = tm.TelemetryRegistry(), tm.TelemetryRegistry()
    a.counter("verify_sigs_total").add(10)
    b.counter("verify_sigs_total").add(5)
    a.histogram("verify_batch_sigs").observe(3)   # bucket 2
    b.histogram("verify_batch_sigs").observe(3)   # same bucket: must sum
    b.histogram("verify_batch_sigs").observe(100)  # bucket 7
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["counters"]["verify_sigs_total"] == 15
    h = merged["histograms"]["verify_batch_sigs"]
    assert h["count"] == 3 and h["sum"] == pytest.approx(106.0)
    assert h["buckets"] == {"2": 2, "7": 1}


def test_collect_cluster_reports_missing_nodes(fresh):
    tm.inc("rounds_total", 2)
    snap = tm.snapshot()
    out = collect_cluster({"A": snap, "B": None, "C": snap})
    assert out["missing"] == ["B"]
    assert set(out["nodes"]) == {"A", "C"}
    assert out["merged"]["counters"]["rounds_total"] == 4


def test_collect_cluster_zero_sample_node_merges_exactly(fresh):
    """A node that served a snapshot but never observed anything (all
    counters 0, no histogram samples) is PRESENT — not missing — and its
    zeros must not perturb the fold (the doctor reads merged counters;
    an idle member silently dropped would skew per-node ratios)."""
    busy, idle = tm.TelemetryRegistry(), tm.TelemetryRegistry()
    busy.counter("verify_sigs_total").add(7)
    busy.histogram("verify_batch_sigs").observe(5)
    out = collect_cluster({"busy": busy.snapshot(),
                           "idle": idle.snapshot()})
    assert out["missing"] == []
    assert set(out["nodes"]) == {"busy", "idle"}
    assert out["merged"]["counters"]["verify_sigs_total"] == 7
    h = out["merged"]["histograms"]["verify_batch_sigs"]
    assert h["count"] == 1 and h["buckets"] == {"3": 1}
    # And the merged view still renders/parses as valid exposition.
    parsed = parse_prometheus(render_prometheus(out["merged"]))
    assert parsed["counters"]["verify_sigs_total"] == 7


def test_merge_tolerates_stale_snapshot_schema(fresh):
    """A stale snapshot — captured by an older build that knew fewer
    metrics (keys absent entirely) and whose histogram block predates
    some fields — merges without KeyError: absent counters contribute 0,
    absent histogram fields default, and the newer node's series all
    survive. This is the rolling-upgrade shape collect_cluster meets."""
    new = tm.TelemetryRegistry()
    new.counter("doctor_runs_total").add(3)
    new.counter("rounds_total").add(10)
    new.histogram("round_wall_seconds").observe(0.25)
    stale = {"counters": {"rounds_total": 4.0},
             # Old shape: no scale, no sum, sparse buckets only.
             "histograms": {"round_wall_seconds": {"count": 2,
                                                   "buckets": {"17": 2}}}}
    merged = merge_snapshots([stale, new.snapshot()])
    assert merged["counters"]["rounds_total"] == 14
    assert merged["counters"]["doctor_runs_total"] == 3
    h = merged["histograms"]["round_wall_seconds"]
    assert h["count"] == 3
    # 0.25 s at the _seconds scale (1e6) lands in bucket 2^18; the stale
    # block's bucket 17 survives beside it with its own count.
    assert h["buckets"] == {"17": 2, "18": 1}
    # The merged histogram still renders as monotonic exposition.
    parse_prometheus(render_prometheus(merged))


def test_merge_disjoint_sparse_buckets_is_exact(fresh):
    """Two nodes whose sparse histograms share NO bucket index merge by
    union — every index survives with its own count, ordered, and the
    cumulative exposition stays monotonic (the power-of-two indices
    align across processes by construction, so this is exact)."""
    a, b = tm.TelemetryRegistry(), tm.TelemetryRegistry()
    a.histogram("verify_batch_sigs").observe(2)     # bucket idx 2
    a.histogram("verify_batch_sigs").observe(2)
    b.histogram("verify_batch_sigs").observe(1000)  # bucket idx 10
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    h = merged["histograms"]["verify_batch_sigs"]
    assert h["buckets"] == {"2": 2, "10": 1}
    assert list(h["buckets"]) == ["2", "10"]  # index-sorted
    assert h["count"] == 3 and h["sum"] == pytest.approx(1004.0)
    parsed = parse_prometheus(render_prometheus(merged))
    cums = [c for _, c in
            parsed["histograms"]["verify_batch_sigs"]["buckets"]]
    assert cums == [2, 3, 3]  # cumulative across the disjoint union


# ---------------------------------------------------------------------------
# Flight recorder: ring, deltas, and the exactly-one-artifact latch
# ---------------------------------------------------------------------------


def test_flight_latches_one_artifact_per_reason(tmp_path, fresh):
    rec = tm.FlightRecorder(str(tmp_path), node="t")
    rec.tick({"sheds": 1, "rate": "ignored-non-numeric"})
    rec.tick({"sheds": 4})
    rec.note("probe", detail="window context")
    p1 = rec.trigger("slo_breach", extra={"rate_tx_s": 480},
                     spans=[{"name": "qos_flush"}])
    p2 = rec.trigger("slo_breach", extra={"rate_tx_s": 960})
    assert p1 == p2 and os.path.exists(p1)
    art = json.loads(open(p1).read())
    assert art["reason"] == "slo_breach"
    assert art["extra"] == {"rate_tx_s": 480}  # first trigger wins
    assert art["spans"] == [{"name": "qos_flush"}]
    # The window carries per-tick DELTAS, not lifetime totals.
    assert art["window"][1]["delta"] == {"sheds": 3}
    assert art["window"][2]["kind"] == "probe"
    # A different reason is a different artifact; the registry counts it.
    p3 = rec.trigger("crash")
    assert p3 != p1 and os.path.exists(p3)
    assert sorted(rec.dumped) == ["crash", "slo_breach"]
    assert tm.snapshot()["counters"]["flight_dumps_total"] == 2


def test_flight_trigger_never_raises(tmp_path, fresh):
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("occupied")
    rec = tm.FlightRecorder(str(blocker / "sub"), node="t")
    assert rec.trigger("crash") is None  # unwritable dir: swallowed
    # Latched even on failure — a broken disk doesn't retry per crash.
    assert "crash" in rec.dumped


def test_ensure_flight_reads_env_and_is_idempotent(tmp_path, fresh,
                                                   monkeypatch):
    monkeypatch.delenv(tm.FLIGHT_ENV, raising=False)
    assert tm.ensure_flight() is None  # no dir anywhere: stays a no-op
    monkeypatch.setenv(tm.FLIGHT_ENV, str(tmp_path))
    fl = tm.ensure_flight(node="envnode")
    assert fl is fresh.flight and fl.node == "envnode"
    assert tm.ensure_flight(node="other") is fl  # idempotent
    path = tm.flight_trigger("fsck_failure", extra={"corrupt": 1})
    assert path is not None and os.path.exists(path)
    assert fl.stats()["dumped"] == {"fsck_failure": path}


# ---------------------------------------------------------------------------
# Trigger matrix: overload spike (admission) and crash (run loop)
# ---------------------------------------------------------------------------


def test_admission_overload_spike_dumps_once(tmp_path, fresh):
    from corda_tpu.qos.admission import SPIKE_SHEDS, AdmissionController
    from corda_tpu.qos.context import LANE_BULK

    fresh.flight = tm.FlightRecorder(str(tmp_path), node="adm")
    # One burst token, effectively no refill: everything after the first
    # request sheds.
    ac = AdmissionController(bulk_rate=1e-6, bulk_burst=1.0)
    sheds = 0
    for _ in range(SPIKE_SHEDS + 25):
        if ac.admit(LANE_BULK) is not None:
            sheds += 1
    assert sheds >= SPIKE_SHEDS
    assert list(fresh.flight.dumped) == ["overload_spike"]
    art = json.loads(open(fresh.flight.dumped["overload_spike"]).read())
    assert art["extra"]["sheds_in_window"] == SPIKE_SHEDS
    # The metric snapshot is captured AT the spike (the 50th shed), not
    # after the loop finished shedding.
    assert art["metrics"]["counters"]["admission_shed_total"] == SPIKE_SHEDS


def test_run_once_crash_dumps_and_reraises(tmp_path, fresh):
    tm.ensure_flight(str(tmp_path), node="crashnode")
    node = Node(NodeConfig(name="CrashNode",
                           base_dir=tmp_path / "CrashNode",
                           network_map=tmp_path / "netmap.json")).start()
    try:
        node.run_once(timeout=0.001)  # healthy round first

        def _boom():
            raise RuntimeError("injected round failure")

        node.smm.poll_services = _boom
        with pytest.raises(RuntimeError, match="injected"):
            node.run_once(timeout=0.001)
    finally:
        node.stop()
    assert list(fresh.flight.dumped) == ["crash"]
    art = json.loads(open(fresh.flight.dumped["crash"]).read())
    assert art["extra"]["node"] == "CrashNode"
    assert "RuntimeError: injected round failure" in art["extra"]["error"]


# ---------------------------------------------------------------------------
# Live round profiler + the node's /metrics surface
# ---------------------------------------------------------------------------


def test_live_rounds_attribute_90pct_and_metrics_endpoint(tmp_path, fresh):
    node = Node(NodeConfig(name="TmNode", base_dir=tmp_path / "TmNode",
                           network_map=tmp_path / "netmap.json",
                           web_port=0)).start()
    try:
        for _ in range(50):
            node.run_once(timeout=0.002)
        bd = tm.format_breakdown(node.smm.metrics["round_phase_s"])
        assert bd["rounds"] == 50
        # The acceptance bound: named phases attribute >= 90% of measured
        # round wall time (live measurement sits ~99.9%).
        assert bd["coverage"] >= 0.9
        assert bd["busiest_phase"] in tm.ROUND_PHASES
        # The registry saw the same rounds through observe_round.
        c = tm.snapshot()["counters"]
        assert c["rounds_total"] == 50
        assert c["round_wall_seconds_total"] == pytest.approx(
            node.smm.metrics["round_phase_s"]["wall"], rel=1e-6)

        base = f"http://127.0.0.1:{node.webserver.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5.0) as resp:
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            parsed = parse_prometheus(resp.read().decode())
        assert set(parsed["counters"]) == set(tm.COUNTER_NAMES)
        assert set(parsed["histograms"]) == set(tm.HISTOGRAM_NAMES)
        assert parsed["counters"]["rounds_total"] >= 50
    finally:
        node.stop()


def test_node_metrics_rpc_carries_round_breakdown(tmp_path, fresh):
    from corda_tpu.node.rpc import NodeRpcOps

    node = Node(NodeConfig(name="RbNode", base_dir=tmp_path / "RbNode",
                           network_map=tmp_path / "netmap.json")).start()
    try:
        for _ in range(25):
            node.run_once(timeout=0.002)
        ops = NodeRpcOps(node)
        nm = ops.node_metrics()
        assert nm["round_breakdown"]["rounds"] == 25
        assert nm["round_breakdown"]["coverage"] >= 0.9
        assert nm["telemetry"]["rounds_total"] == 25
        ts = ops.telemetry_snapshot()
        assert ts["node"] == "RbNode" and ts["armed"] is True
        assert set(ts["snapshot"]["histograms"]) == set(tm.HISTOGRAM_NAMES)
    finally:
        node.stop()


def test_pipelined_live_rounds_attribute_90pct_with_overlap(tmp_path, fresh):
    """The >= 90%-attribution acceptance bound extends to the PIPELINED
    round loop: a raft leader whose apply runs on the detached executor
    still attributes >= 90% of consensus-thread wall time across the six
    phases, while the executor's apply seconds surface in the ``overlap``
    block BESIDE them — counted once, never inside coverage."""
    import time as _t

    from corda_tpu.contracts.structures import StateRef
    from corda_tpu.crypto.hashes import SecureHash
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.party import Party
    from corda_tpu.node.services.raft import PutAllCommand

    node = Node(NodeConfig(name="PipeNode", base_dir=tmp_path / "PipeNode",
                           notary="raft-simple", raft_cluster=("PipeNode",),
                           network_map=tmp_path / "netmap.json")).start()
    try:
        deadline = _t.monotonic() + 15.0
        member = node.raft_member
        while member.role != "leader":
            node.run_once(timeout=0.002)
            assert _t.monotonic() < deadline, "no leader"
        assert member.config.pipeline is True
        party = Party("Client",
                      KeyPair.generate(b"\x01" * 32).public.composite)
        i = 0
        # Drive committed work through the loop until some executor apply
        # wall time lands inside a measured round window.
        while node.smm.metrics["round_phase_s"].get(
                "overlap_apply", 0.0) <= 0.0:
            member.submit(PutAllCommand(
                (StateRef(SecureHash.sha256(b"s%d" % i), 0),),
                SecureHash.sha256(b"t%d" % i), party, b"r%d" % i))
            node.run_once(timeout=0.002)
            i += 1
            assert _t.monotonic() < deadline, "no overlap observed"
        for _ in range(20):  # a healthy tail of ordinary rounds
            node.run_once(timeout=0.002)
        member.quiesce_apply()
        rp = node.smm.metrics["round_phase_s"]
        bd = tm.format_breakdown(rp)
        assert bd["coverage"] >= 0.9
        assert bd["overlap"]["apply"]["total_s"] > 0.0
        assert "overlap_apply" not in bd["phases"]  # no double count
        assert sum(p["total_s"] for p in bd["phases"].values()) \
            <= bd["wall_s"] + 1e-9
        c = tm.snapshot()["counters"]
        assert c["round_overlap_apply_seconds_total"] > 0.0
        assert c["raft_apply_batches_total"] >= 1
        stamp = member.stamp()
        assert stamp["pipeline"] is True
        assert stamp["apply_batches"] >= 1
        assert stamp["overlap_s"]["apply"] > 0.0
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# Sidecar OP_METRICS
# ---------------------------------------------------------------------------


@pytest.fixture()
def sock_path():
    # Short /tmp path on purpose: AF_UNIX paths cap at ~108 bytes.
    d = tempfile.mkdtemp(prefix="tmx-", dir="/tmp")
    try:
        yield os.path.join(d, "s.sock")
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_sidecar_serves_prometheus_over_op_metrics(sock_path, fresh):
    srv = SidecarServer(sock_path, verifier=CpuVerifier(),
                        coalesce_us=0).start()
    try:
        cli = SidecarVerifier(sock_path, device_min_sigs=0)
        cli.verify_batch([VerifyJob(bytes(32), bytes(32), bytes(64))] * 3)
        text = fetch_sidecar_metrics(sock_path)
        parsed = parse_prometheus(text)
        assert set(parsed["counters"]) == set(tm.COUNTER_NAMES)
        assert parsed["counters"]["sidecar_requests_total"] >= 1
        assert parsed["counters"]["sidecar_sigs_total"] >= 3
        h = parsed["histograms"]["sidecar_batch_sigs"]
        assert h["count"] >= 1
    finally:
        srv.stop()
