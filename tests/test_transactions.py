"""L1 transaction layer: ids, builder, signature checking, platform rules,
tear-offs.

Mirrors the reference's TransactionTests / WireTransaction usage patterns
(reference: core/src/test/kotlin/net/corda/core/contracts/TransactionTests.kt,
PartialMerkleTreeTest.kt tear-off sections).
"""

import dataclasses

import pytest

from corda_tpu.contracts import (
    Command,
    StateAndRef,
    StateRef,
    Timestamp,
    TransactionState,
    NotaryChangeInWrongTransactionType,
    SignersMissing,
    InvalidNotaryChange,
    ContractRejection,
    TransactionMissingEncumbranceException,
)
from corda_tpu.crypto import SecureHash, SignatureError
from corda_tpu.serialization.codec import deserialize, register, serialize
from corda_tpu.testing import (
    ALICE,
    ALICE_KEY,
    BOB,
    BOB_KEY,
    DUMMY_NOTARY,
    DUMMY_NOTARY_KEY,
    MEGA_CORP,
    DummyContract,
    DummyCreate,
    DummyMove,
    DummySingleOwnerState,
)
from corda_tpu.transactions import (
    LedgerTransaction,
    SignaturesMissingException,
    SignedTransaction,
    TransactionBuilder,
    FilterFuns,
    FilteredTransaction,
    NotaryChangeTransactionType,
)
from corda_tpu.transactions.builder import NotaryChangeBuilder


def issue_tx() -> TransactionBuilder:
    return DummyContract.generate_initial(ALICE.ref(b"\x01"), 42, DUMMY_NOTARY)


def move_tx() -> TransactionBuilder:
    """A move spends an input, so the notary key lands in must_sign."""
    prior = issue_tx().to_wire_transaction().out_ref(0)
    return DummyContract.move(prior, BOB.owning_key)


class TestWireTransaction:
    def test_id_is_stable_over_serialization(self):
        wtx = issue_tx().to_wire_transaction()
        restored = deserialize(serialize(wtx).bytes)
        assert restored.id == wtx.id
        assert restored == wtx

    def test_id_changes_with_content(self):
        a = DummyContract.generate_initial(ALICE.ref(b"\x01"), 42, DUMMY_NOTARY)
        b = DummyContract.generate_initial(ALICE.ref(b"\x01"), 43, DUMMY_NOTARY)
        assert a.to_wire_transaction().id != b.to_wire_transaction().id

    def test_id_independent_of_signatures(self):
        builder = issue_tx()
        unsigned_id = builder.to_wire_transaction().id
        builder.sign_with(ALICE_KEY)
        stx = builder.to_signed_transaction(check_sufficient_signatures=False)
        assert stx.id == unsigned_id

    def test_inputs_require_notary(self):
        from corda_tpu.transactions.wire import WireTransaction

        with pytest.raises(ValueError):
            WireTransaction(inputs=(StateRef(SecureHash.zero(), 0),), notary=None)

    def test_timestamp_requires_notary(self):
        from corda_tpu.transactions.wire import WireTransaction

        with pytest.raises(ValueError):
            WireTransaction(timestamp=Timestamp.around(10**15, 10**6))

    def test_out_ref(self):
        wtx = issue_tx().to_wire_transaction()
        ref = wtx.out_ref(0)
        assert ref.ref == StateRef(wtx.id, 0)
        assert ref.state.data.magic_number == 42


class TestSignedTransaction:
    def test_verify_signatures_happy_path(self):
        builder = issue_tx()
        builder.sign_with(ALICE_KEY).sign_with(DUMMY_NOTARY_KEY)
        stx = builder.to_signed_transaction()
        wtx = stx.verify_signatures()
        assert wtx.id == stx.id

    def test_missing_notary_sig_reported(self):
        builder = move_tx()
        builder.sign_with(ALICE_KEY)
        stx = builder.to_signed_transaction(check_sufficient_signatures=False)
        with pytest.raises(SignaturesMissingException) as exc:
            stx.verify_signatures()
        assert "notary" in exc.value.descriptions

    def test_allowed_to_be_missing(self):
        builder = move_tx()
        builder.sign_with(ALICE_KEY)
        stx = builder.to_signed_transaction(check_sufficient_signatures=False)
        stx.verify_signatures(DUMMY_NOTARY.owning_key)

    def test_corrupt_signature_rejected(self):
        builder = issue_tx()
        builder.sign_with(ALICE_KEY).sign_with(DUMMY_NOTARY_KEY)
        stx = builder.to_signed_transaction()
        bad_sig = dataclasses.replace(stx.sigs[0], bytes=b"\x01" * 64)
        bad = dataclasses.replace(stx, sigs=(bad_sig, stx.sigs[1]))
        with pytest.raises(SignatureError):
            bad.verify_signatures()

    def test_wrong_key_signature_rejected(self):
        builder = issue_tx()
        builder.sign_with(ALICE_KEY).sign_with(DUMMY_NOTARY_KEY)
        stx = builder.to_signed_transaction()
        # Swap the claimed signer: math check must fail.
        forged = dataclasses.replace(stx.sigs[0], by=BOB.owning_key.single_key)
        bad = dataclasses.replace(stx, sigs=(forged, stx.sigs[1]))
        with pytest.raises(SignatureError):
            bad.verify_signatures()

    def test_composite_key_fulfilment_via_any_member(self):
        from corda_tpu.crypto import CompositeKey

        cluster = (
            CompositeKey.Builder()
            .add_keys(ALICE.owning_key.single_key, BOB.owning_key.single_key)
            .build(threshold=1)
        )
        cluster_party = type(DUMMY_NOTARY)("Cluster", cluster)
        builder = DummyContract.generate_initial(ALICE.ref(b"\x01"), 7, cluster_party)
        builder.sign_with(ALICE_KEY)  # command key
        builder.sign_with(BOB_KEY)  # one cluster member satisfies 1-of-2
        stx = builder.to_signed_transaction(check_sufficient_signatures=False)
        stx.verify_signatures()

    def test_sign_requires_all_before_freeze(self):
        builder = move_tx()
        builder.sign_with(ALICE_KEY)
        with pytest.raises(ValueError):
            builder.to_signed_transaction()  # notary key missing


def resolved(builder: TransactionBuilder) -> LedgerTransaction:
    """Resolve a tx whose inputs came from out_ref()s already in the builder."""
    wtx = builder.to_wire_transaction()
    from corda_tpu.contracts import AuthenticatedObject

    return LedgerTransaction(
        inputs=(),
        outputs=wtx.outputs,
        commands=tuple(
            AuthenticatedObject(c.signers, (), c.value) for c in wtx.commands
        ),
        attachments=(),
        id=wtx.id,
        notary=wtx.notary,
        must_sign=wtx.signers,
        timestamp=wtx.timestamp,
        type=wtx.type,
    )


class _Rejector(DummyContract):
    def verify(self, tx):
        raise ValueError("no")


_REJECTOR = _Rejector()


@register
@dataclasses.dataclass(frozen=True)
class _RejectedState(DummySingleOwnerState):
    @property
    def contract(self):
        return _REJECTOR


@register
@dataclasses.dataclass(frozen=True)
class _EncumberedState(DummySingleOwnerState):
    enc: int = 0

    @property
    def encumbrance(self):
        return self.enc


class TestPlatformRules:
    def test_general_verify_accepts_dummy(self):
        resolved(issue_tx()).verify()

    def test_missing_signer_detected(self):
        ltx = resolved(issue_tx())
        stripped = dataclasses.replace(ltx, must_sign=())
        with pytest.raises(SignersMissing):
            stripped.verify()

    def test_contract_rejection_wraps_cause(self):
        builder = TransactionBuilder(notary=DUMMY_NOTARY)
        builder.add_output_state(_RejectedState(1, ALICE.owning_key))
        builder.add_command(Command(DummyCreate(), (ALICE.owning_key,)))
        with pytest.raises(ContractRejection):
            resolved(builder).verify()

    def test_notary_change_in_general_tx_rejected(self):
        issue = issue_tx()
        issue.sign_with(ALICE_KEY).sign_with(DUMMY_NOTARY_KEY)
        prior = issue.to_wire_transaction().out_ref(0)

        move = DummyContract.move(prior, BOB.owning_key)
        wtx = move.to_wire_transaction()
        # Tamper: outputs claim a different notary.
        hijacked = dataclasses.replace(
            wtx, outputs=(TransactionState(wtx.outputs[0].data, MEGA_CORP),)
        )
        ltx = LedgerTransaction(
            inputs=(StateAndRef(prior.state, prior.ref),),
            outputs=hijacked.outputs,
            commands=(),
            attachments=(),
            id=hijacked.id,
            notary=DUMMY_NOTARY,
            must_sign=hijacked.signers,
            timestamp=None,
            type=hijacked.type,
        )
        with pytest.raises(NotaryChangeInWrongTransactionType):
            ltx.verify()

    def test_encumbrance_output_self_reference_rejected(self):
        builder = TransactionBuilder(notary=DUMMY_NOTARY)
        builder.add_output_state(_EncumberedState(1, ALICE.owning_key, enc=0))  # self-ref
        builder.add_command(Command(DummyCreate(), (ALICE.owning_key,)))
        with pytest.raises(TransactionMissingEncumbranceException):
            resolved(builder).verify()


class TestNotaryChange:
    def _prior(self) -> StateAndRef:
        issue = issue_tx()
        return issue.to_wire_transaction().out_ref(0)

    def test_notary_change_roundtrip(self):
        prior = self._prior()
        builder = NotaryChangeBuilder(DUMMY_NOTARY)
        builder.add_input_state(prior)
        builder.add_output_state(prior.state.with_notary(MEGA_CORP))
        wtx = builder.to_wire_transaction()
        assert isinstance(wtx.type, NotaryChangeTransactionType)
        # participants auto-added as signers
        assert ALICE.owning_key in wtx.signers
        ltx = LedgerTransaction(
            inputs=(prior,),
            outputs=wtx.outputs,
            commands=(),
            attachments=(),
            id=wtx.id,
            notary=wtx.notary,
            must_sign=wtx.signers,
            timestamp=None,
            type=wtx.type,
        )
        ltx.verify()

    def test_state_mutation_rejected(self):
        prior = self._prior()
        builder = NotaryChangeBuilder(DUMMY_NOTARY)
        builder.add_input_state(prior)
        mutated = DummySingleOwnerState(99, ALICE.owning_key)
        builder.add_output_state(TransactionState(mutated, MEGA_CORP))
        wtx = builder.to_wire_transaction()
        ltx = LedgerTransaction(
            inputs=(prior,),
            outputs=wtx.outputs,
            commands=(),
            attachments=(),
            id=wtx.id,
            notary=wtx.notary,
            must_sign=wtx.signers,
            timestamp=None,
            type=wtx.type,
        )
        with pytest.raises(InvalidNotaryChange):
            ltx.verify()


class TestFilteredTransaction:
    def test_tear_off_commands_only(self):
        builder = issue_tx()
        wtx = builder.to_wire_transaction()
        ftx = wtx.build_filtered_transaction(
            FilterFuns(filter_commands=lambda c: isinstance(c.value, DummyCreate))
        )
        assert ftx.verify(wtx.id)
        assert len(ftx.filtered_leaves.commands) == 1
        assert ftx.filtered_leaves.outputs == ()

    def test_tear_off_does_not_verify_against_other_tx(self):
        wtx = issue_tx().to_wire_transaction()
        other = DummyContract.generate_initial(
            ALICE.ref(b"\x01"), 43, DUMMY_NOTARY
        ).to_wire_transaction()
        ftx = wtx.build_filtered_transaction(
            FilterFuns(filter_commands=lambda c: True)
        )
        assert not ftx.verify(other.id)

    def test_tear_off_roundtrips(self):
        wtx = issue_tx().to_wire_transaction()
        ftx = wtx.build_filtered_transaction(FilterFuns(filter_outputs=lambda o: True))
        restored = deserialize(serialize(ftx).bytes)
        assert restored.verify(wtx.id)
