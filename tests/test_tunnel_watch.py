"""Tunnel-watcher unit tier: report parsing and capture gating only — the
probe/bench loop spawns real subprocesses and is exercised operationally,
not in CI (the suite must never depend on tunnel liveness)."""

import json

from corda_tpu.tools import tunnel_watch


def test_device_backed_gating():
    assert not tunnel_watch.device_backed(None)
    assert not tunnel_watch.device_backed({})
    assert not tunnel_watch.device_backed({"device": "unavailable"})
    assert tunnel_watch.device_backed({"device": "TPU v5e", "value": 1.0})


def test_run_bench_parses_last_json_line(monkeypatch, tmp_path):
    # bench prints exactly one JSON line, but warm-up chatter may precede
    # it on stdout; the parser must take the last JSON-looking line.
    bench = tmp_path / "fake_bench.py"
    bench.write_text(
        "print('warming caches...')\n"
        "print('{\"metric\": \"verified_sigs_per_sec\", \"value\": 42.0, "
        "\"device\": \"TPU\"}')\n")
    report = tunnel_watch.run_bench(str(bench), timeout_s=150.0)
    assert report == {"metric": "verified_sigs_per_sec", "value": 42.0,
                      "device": "TPU"}
    assert tunnel_watch.device_backed(report)


def test_run_bench_none_on_garbage(tmp_path):
    bench = tmp_path / "fake_bench.py"
    bench.write_text("print('no json here')\n")
    assert tunnel_watch.run_bench(str(bench), timeout_s=150.0) is None


def test_capture_written_only_when_device_backed(tmp_path, monkeypatch):
    out = tmp_path / "cap.json"
    calls = {"probe": 0, "bench": 0}

    def fake_probe(timeout_s):
        calls["probe"] += 1
        return True

    reports = [
        {"device": "unavailable", "value": 0.0},       # first: degraded
        {"device": "TPU v5e", "value": 123456.0},      # then: real
    ]

    def fake_bench(path, timeout_s):
        calls["bench"] += 1
        return reports[calls["bench"] - 1]

    monkeypatch.setattr(tunnel_watch, "probe_once", fake_probe)
    monkeypatch.setattr(tunnel_watch, "run_bench", fake_bench)
    monkeypatch.setattr(tunnel_watch.time, "sleep", lambda s: None)
    rc = tunnel_watch.main([
        "--out", str(out), "--interval", "0", "--consecutive", "2",
        "--max-hours", "1"])
    assert rc == 0
    assert calls["bench"] == 2  # degraded report did NOT stop the watch
    assert json.loads(out.read_text())["value"] == 123456.0
