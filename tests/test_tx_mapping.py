"""Flow→transaction provenance mapping (reference: core/.../node/services/
StateMachineRecordedTransactionMappingStorage.kt; RPC exposure at
node/.../messaging/CordaRPCOps.kt:86): every transaction a flow records is
mapped to the flow's run id, durably, and the join is visible over RPC as
a poll snapshot plus live ("tx_recorded", ...) push events.
"""

import threading

import pytest

from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.provider import CpuVerifier
from corda_tpu.flows import FinalityFlow
from corda_tpu.testing import DummyContract
from corda_tpu.testing.mock_network import MockNetwork


@pytest.fixture()
def net():
    network = MockNetwork(verifier=CpuVerifier())
    yield network
    network.stop_nodes()


def _issue_and_finalise(net, node, notary_party, magic=3, recipients=()):
    builder = DummyContract.generate_initial(
        node.identity.ref(b"\x00"), magic, notary_party)
    builder.sign_with(node.key)
    stx = builder.to_signed_transaction()
    handle = node.start_flow(FinalityFlow(stx, tuple(recipients)))
    net.run_network()
    handle.result.result()
    return stx, handle


def test_flow_recording_lands_in_mapping_storage(net):
    notary = net.create_notary_node("Notary")
    alice = net.create_node("Alice")
    stx, handle = _issue_and_finalise(net, alice, notary.identity)

    mapping = alice.services.storage_service \
        .state_machine_recorded_transaction_mapping
    got = {(m.run_id, m.tx_id) for m in mapping.mappings()}
    assert (handle.run_id, stx.id) in got


def test_mapping_dedupes_and_notifies_once():
    from corda_tpu.node.services.inmemory import (
        InMemoryTransactionMappingStorage,
    )

    storage = InMemoryTransactionMappingStorage()
    seen = []
    storage.subscribe(seen.append)
    tx_id = SecureHash.sha256(b"tx")
    storage.add_mapping(b"run-1", tx_id)
    storage.add_mapping(b"run-1", tx_id)  # checkpoint replay re-record
    storage.add_mapping(b"run-2", tx_id)  # a second flow touching the tx
    assert len(storage.mappings()) == 2
    assert len(seen) == 2
    assert seen[0].run_id == b"run-1" and seen[0].tx_id == tx_id


def test_db_mapping_survives_restart(tmp_path):
    from corda_tpu.node.services.persistence import (
        DBTransactionMappingStorage,
        NodeDatabase,
    )

    path = tmp_path / "node.db"
    db = NodeDatabase(path)
    storage = DBTransactionMappingStorage(db)
    tx_id = SecureHash.sha256(b"durable-tx")
    storage.add_mapping(b"run-9", tx_id)
    storage.add_mapping(b"run-9", tx_id)  # idempotent
    db.close()

    db2 = NodeDatabase(path)  # the rebirth
    storage2 = DBTransactionMappingStorage(db2)
    got = storage2.mappings()
    assert [(m.run_id, m.tx_id) for m in got] == [(b"run-9", tx_id)]
    db2.close()


def test_mapping_over_rpc_poll_and_push(tmp_path):
    """A real node: the RPC snapshot carries the mapping and the push
    stream announces it live as a ("tx_recorded", run_id, tx_id) event."""
    from corda_tpu.node.config import NodeConfig
    from corda_tpu.node.node import Node
    from corda_tpu.node.rpc import RpcClient

    node = Node(NodeConfig(
        name="Prov", base_dir=tmp_path / "Prov",
        network_map=tmp_path / "netmap.json", notary="simple",
        rpc_users=({"username": "ops", "password": "pw",
                    "permissions": ["ALL"]},))).start()
    stop = threading.Event()
    pumper = threading.Thread(
        target=lambda: [node.run_once(timeout=0.01)
                        for _ in iter(stop.is_set, True)], daemon=True)
    pumper.start()
    client = RpcClient(node.messaging.my_address, "ops", "pw")
    try:
        import corda_tpu.tools.demo_cordapp  # noqa: F401  (registers the flow)

        got: list = []
        client.subscribe_changes(lambda events, cursor: got.extend(events))
        handle = client.call(
            "start_flow_dynamic", "IssueAndNotariseFlow", (41,))
        import time

        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            done, _ = client.call("flow_result", handle.run_id)
            if done:
                break
            client.poll_push()
            time.sleep(0.05)
        else:
            pytest.fail("demo flow did not finish")

        snapshot = client.call("state_machine_recorded_transaction_mapping")
        by_run = [m for m in snapshot if m.run_id == handle.run_id]
        # DemoIssueAndMove records the issue and the notarised move.
        assert len(by_run) == 2, snapshot
        for m in by_run:
            assert client.call("verified_transaction", m.tx_id) is not None

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            recorded = [e for e in got if e[0] == "tx_recorded"]
            if len(recorded) >= 2:
                break
            client.poll_push()
            time.sleep(0.05)
        recorded = [e for e in got if e[0] == "tx_recorded"]
        assert {e[1] for e in recorded} == {handle.run_id}
        assert {bytes(e[2]) for e in recorded} == {
            m.tx_id.bytes for m in by_run}
    finally:
        client.close()
        stop.set()
        pumper.join(timeout=2)
        node.stop()


def test_responder_side_records_provenance_too(net):
    """A two-party broadcast: the RECIPIENT's responder flow (data-vending
    NotifyTransactionHandler) records the tx with ITS OWN run id — both
    ledgers can attribute the tx to the protocol run that delivered it
    (reference: every recordTransactions call site feeds the mapping,
    ServiceHubInternal)."""
    notary = net.create_notary_node("Notary")
    alice = net.create_node("Alice")
    bob = net.create_node("Bob")
    stx, handle = _issue_and_finalise(net, alice, notary.identity, magic=9,
                                      recipients=(bob.identity,))

    for node, run_id in ((alice, handle.run_id), (bob, None)):
        mapping = node.services.storage_service \
            .state_machine_recorded_transaction_mapping
        entries = [m for m in mapping.mappings() if m.tx_id == stx.id]
        assert entries, f"{node.identity.name} has no mapping for the tx"
        if run_id is not None:
            assert entries[0].run_id == run_id
        else:
            # Bob's mapping belongs to his responder flow — a run id of
            # HIS state machine, not Alice's.
            assert entries[0].run_id != handle.run_id
