"""Universal (composable) contract tests.

Mirrors the reference's experimental universal-contract suite (reference:
experimental/src/test/kotlin/net/corda/contracts/universal/
{ZeroCouponBond,FXSwap,Cap,RollOutTests}.kt) at the rules tier: products are
arrangement values, and the one UniversalContract verifies issue, exercise,
party replacement, oracle fixing, and schedule roll-out structurally.
"""

import pytest

from corda_tpu.contracts.structures import Timestamp
from corda_tpu.contracts.universal import (
    SCALE,
    ZERO,
    Actions,
    All,
    Compare,
    Const,
    Continuation,
    EndDate,
    Fixing,
    GT,
    Interest,
    PosPart,
    RollOut,
    StartDate,
    TimeCondition,
    Transfer,
    UAction,
    UApplyFixes,
    UIssue,
    UMove,
    UniversalState,
    actions,
    after,
    all_of,
    arrange,
    before,
    eval_amount,
    eval_condition,
    fixing,
    interest,
    involved_parties,
    liable_parties,
    reduce_rollout,
    replace_fixings,
    replace_party,
    to_quanta,
    transfer,
)
from corda_tpu.finance.types import Tenor, date_to_days
from corda_tpu.flows.oracle import Fix, FixOf
from corda_tpu.crypto.keys import KeyPair
from corda_tpu.crypto.party import Party
from corda_tpu.serialization.codec import serialize, deserialize
from corda_tpu.testing.ledger_dsl import ledger

import datetime as dt

ACME = Party.of("ACME", KeyPair.generate(b"\x61" * 32).public)
HIGH_ST = Party.of("HighStreetBank", KeyPair.generate(b"\x62" * 32).public)
MOMENTUM = Party.of("Momentum", KeyPair.generate(b"\x63" * 32).public)
NOTARY = Party.of("Notary", KeyPair.generate(b"\x64" * 32).public)

MATURITY = date_to_days(dt.date(2017, 9, 1))
_DAY_MICROS = 86_400 * 1_000_000


def day_ts(day, slack_days=0):
    """A timestamp window proving the tx happened on/after `day`."""
    return Timestamp(day * _DAY_MICROS, (day + slack_days + 1) * _DAY_MICROS)


def ustate(arrangement):
    keys = sorted(involved_parties(arrangement),
                  key=lambda k: k.to_base58_string())
    return UniversalState(tuple(keys), arrangement)


def zcb(amount=to_quanta(100_000)):
    """Zero-coupon bond: after maturity ACME may demand payment from the bank
    (reference: ZeroCouponBond.kt)."""
    return actions(
        arrange("execute", after(MATURITY), ACME,
                transfer(amount, "USD", HIGH_ST, ACME)))


class TestStructure:
    def test_liable_and_involved_parties(self):
        contract = zcb()
        assert liable_parties(contract) == frozenset({HIGH_ST.owning_key})
        assert involved_parties(contract) == frozenset(
            {HIGH_ST.owning_key, ACME.owning_key})

    def test_sole_actor_not_liable(self):
        # A party whose obligation only they can trigger is not "liable".
        give_away = actions(
            arrange("donate", after(MATURITY), ACME,
                    transfer(to_quanta(1), "USD", ACME, HIGH_ST)))
        assert liable_parties(give_away) == frozenset()

    def test_replace_party(self):
        moved = replace_party(zcb(), ACME, MOMENTUM)
        assert liable_parties(moved) == frozenset({HIGH_ST.owning_key})
        assert involved_parties(moved) == frozenset(
            {HIGH_ST.owning_key, MOMENTUM.owning_key})

    def test_arrangements_serialize_canonically(self):
        contract = zcb()
        blob = serialize(contract)
        assert deserialize(blob) == contract
        # structural equality is order-insensitive (frozensets)
        both = all_of(zcb(), transfer(1, "EUR", ACME, HIGH_ST))
        assert deserialize(serialize(both)) == both


class TestEval:
    def test_fixed_point_arithmetic(self):
        p = (Const(to_quanta(3)) * Const(to_quanta(2))
             - Const(to_quanta(1))) // Const(to_quanta(5))
        assert eval_amount(None, p) == to_quanta(1)

    def test_pospart_is_option_payoff(self):
        assert eval_amount(None, PosPart(Const(-5))) == 0
        assert eval_amount(None, PosPart(Const(7))) == 7

    def test_interest_act360(self):
        p = interest(to_quanta(1_000_000), "ACT/360", Const(5 * SCALE),
                     Const(0), Const(360))
        assert eval_amount(None, p) == to_quanta(50_000)

    def test_time_conditions(self):
        class Tx:
            timestamp = day_ts(MATURITY)

        assert eval_condition(Tx, after(MATURITY))
        assert not eval_condition(Tx, before(MATURITY - 1))
        assert eval_condition(Tx, before(MATURITY + 2))

    def test_compare(self):
        class Tx:
            timestamp = None

        assert eval_condition(Tx, Compare(Const(3), GT, Const(2)))


class TestZeroCouponBond:
    """reference: ZeroCouponBond.kt — issue, transfer (move), execute."""

    def test_issue_requires_liable_signature(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output("zcb", ustate(zcb()))
            tx.command(UIssue(), ACME.owning_key)
            tx.fails_with("liable parties")
        with l.transaction() as tx:
            tx.output("zcb", ustate(zcb()))
            tx.command(UIssue(), HIGH_ST.owning_key)
            tx.verifies()

    def test_execute_after_maturity(self):
        settlement = transfer(Const(to_quanta(100_000)), "USD", HIGH_ST, ACME)
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.output("zcb", ustate(zcb()))
            tx.command(UIssue(), HIGH_ST.owning_key)
            tx.verifies()
        with l.transaction() as tx:
            tx.input("zcb")
            tx.output("settled", ustate(settlement))
            tx.command(UAction("execute"), ACME.owning_key)
            with tx.tweak() as tw:
                tw.fails_with("timestamped")
            tx.timestamp(day_ts(MATURITY - 10))
            with tx.tweak() as tw:
                tw.fails_with("condition must be met")
            tx.timestamp(day_ts(MATURITY))
            tx.verifies()

    def test_execute_needs_an_actor_signature(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(zcb()))
            tx.output(None, ustate(
                transfer(Const(to_quanta(100_000)), "USD", HIGH_ST, ACME)))
            tx.command(UAction("execute"), HIGH_ST.owning_key)
            tx.timestamp(day_ts(MATURITY))
            tx.fails_with("authorized")

    def test_wrong_output_rejected(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(zcb()))
            tx.output(None, ustate(
                transfer(Const(to_quanta(50_000)), "USD", HIGH_ST, ACME)))
            tx.command(UAction("execute"), ACME.owning_key)
            tx.timestamp(day_ts(MATURITY))
            tx.fails_with("match action result")

    def test_move_to_new_party(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(zcb()))
            tx.output(None, ustate(replace_party(zcb(), ACME, MOMENTUM)))
            tx.command(UMove(ACME, MOMENTUM), HIGH_ST.owning_key)
            tx.verifies()
        with l.transaction() as tx:
            tx.input(ustate(zcb()))
            tx.output(None, ustate(replace_party(zcb(), ACME, MOMENTUM)))
            tx.command(UMove(ACME, MOMENTUM), MOMENTUM.owning_key)
            tx.fails_with("liable parties")


class TestFXSwap:
    """reference: FXSwap.kt — one action settles two legs (multi-output)."""

    def setup_method(self):
        self.swap = actions(
            arrange("execute", after(MATURITY), {ACME, HIGH_ST},
                    all_of(
                        transfer(to_quanta(1_200_000), "USD", ACME, HIGH_ST),
                        transfer(to_quanta(1_000_000), "EUR", HIGH_ST, ACME))))

    def test_both_parties_liable(self):
        assert liable_parties(self.swap) == frozenset(
            {ACME.owning_key, HIGH_ST.owning_key})

    def test_execute_splits_into_two_outputs(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.swap))
            tx.output(None, ustate(transfer(
                Const(to_quanta(1_200_000)), "USD", ACME, HIGH_ST)))
            tx.output(None, ustate(transfer(
                Const(to_quanta(1_000_000)), "EUR", HIGH_ST, ACME)))
            tx.command(UAction("execute"), ACME.owning_key)
            tx.timestamp(day_ts(MATURITY))
            tx.verifies()

    def test_half_settlement_rejected(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.swap))
            tx.output(None, ustate(transfer(
                Const(to_quanta(1_200_000)), "USD", ACME, HIGH_ST)))
            tx.command(UAction("execute"), ACME.owning_key)
            tx.timestamp(day_ts(MATURITY))
            tx.fails_with("match action result")

    def test_duplicate_output_mint_rejected(self):
        # Round-2 advisor finding: outputs [X, Y, Y] compared equal to
        # All{X, Y} because all_of's frozenset collapses duplicates — an
        # authorized actor could mint a duplicate obligation state. The
        # multiset comparison must reject the duplicated leg.
        usd_leg = transfer(
            Const(to_quanta(1_200_000)), "USD", ACME, HIGH_ST)
        eur_leg = transfer(
            Const(to_quanta(1_000_000)), "EUR", HIGH_ST, ACME)
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.swap))
            tx.output(None, ustate(usd_leg))
            tx.output(None, ustate(eur_leg))
            tx.output(None, ustate(eur_leg))
            tx.command(UAction("execute"), ACME.owning_key)
            tx.timestamp(day_ts(MATURITY))
            tx.fails_with("match action result")


class TestFixings:
    """reference: Caplet.kt/Cap.kt fixing flow — UApplyFixes substitutes an
    oracle-attested rate into the product."""

    def setup_method(self):
        fix_day = date_to_days(dt.date(2017, 3, 1))
        self.fix_of = FixOf("LIBOR", fix_day, "3M")
        rate = fixing("LIBOR", fix_day, "3M", MOMENTUM)  # MOMENTUM = oracle
        notional = to_quanta(10_000_000)
        self.capped = actions(
            arrange("exercise", after(MATURITY), ACME,
                    transfer(
                        PosPart(Interest(Const(notional), "ACT/360",
                                         rate - Const(4 * SCALE),
                                         Const(fix_day), Const(MATURITY))),
                        "USD", HIGH_ST, ACME)))
        self.fixed_value = 5 * SCALE  # 5%

    def fixed_product(self):
        return replace_fixings(self.capped, {self.fix_of: self.fixed_value})

    def test_apply_fixes(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.capped))
            tx.output(None, ustate(self.fixed_product()))
            tx.command(UApplyFixes((Fix(self.fix_of, self.fixed_value),)),
                       ACME.owning_key)
            tx.command(Fix(self.fix_of, self.fixed_value), MOMENTUM.owning_key)
            tx.verifies()

    def test_unattested_fix_rejected(self):
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.capped))
            tx.output(None, ustate(self.fixed_product()))
            tx.command(UApplyFixes((Fix(self.fix_of, self.fixed_value),)),
                       ACME.owning_key)
            tx.fails_with("attested")

    def test_fix_signed_by_wrong_party_rejected(self):
        # ACME fabricates the fix and self-signs the Fix command: the product
        # pins MOMENTUM as the LIBOR oracle, so this must not verify.
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.capped))
            tx.output(None, ustate(self.fixed_product()))
            tx.command(UApplyFixes((Fix(self.fix_of, self.fixed_value),)),
                       ACME.owning_key)
            tx.command(Fix(self.fix_of, self.fixed_value), ACME.owning_key)
            tx.fails_with("attested")

    def test_fix_attesting_different_value_rejected(self):
        # Oracle signed 5%, the command claims 9%: signature over a different
        # value is not attestation.
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.capped))
            tx.output(None, ustate(replace_fixings(
                self.capped, {self.fix_of: 9 * SCALE})))
            tx.command(UApplyFixes((Fix(self.fix_of, 9 * SCALE),)),
                       ACME.owning_key)
            tx.command(Fix(self.fix_of, self.fixed_value),
                       MOMENTUM.owning_key)
            tx.fails_with("attested")

    def test_superfluous_fix_rejected(self):
        bogus = FixOf("LIBOR", 1, "6M")
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.capped))
            tx.output(None, ustate(self.fixed_product()))
            tx.command(UApplyFixes((Fix(self.fix_of, self.fixed_value),
                                    Fix(bogus, 1))), ACME.owning_key)
            tx.command(Fix(self.fix_of, self.fixed_value), MOMENTUM.owning_key)
            tx.command(Fix(bogus, 1), MOMENTUM.owning_key)
            tx.fails_with("relevant fixing")

    def test_fixed_product_evaluates(self):
        fixed = self.fixed_product()
        action = next(iter(fixed.actions))
        amount = eval_amount(None, action.arrangement.amount)
        days = MATURITY - self.fix_of.for_day
        expected = (to_quanta(10_000_000) * (1 * SCALE) * days) \
            // (100 * SCALE * 360)
        assert amount == expected > 0


class TestIRS:
    """Full interest-rate-swap cashflow schedule on the universal DSL
    (reference: experimental/.../universal/IRS.kt contractInitial /
    contractAfterFixingFirst / contractAfterExecutionFirst), driven through
    the ledger for two periods: fix -> net-settle -> roll -> fix again."""

    START = date_to_days(dt.date(2016, 9, 1))
    END = date_to_days(dt.date(2018, 9, 1))

    def setup_method(self):
        from corda_tpu.finance.irs import interest_rate_swap

        self.swap = interest_rate_swap(
            notional=to_quanta(50_000_000), currency="EUR",
            fixed_rate=SCALE // 2,  # 0.5%
            floating_index="LIBOR", index_tenor="3M", oracle=MOMENTUM,
            fixed_leg_payer=ACME, floating_leg_payer=HIGH_ST,
            start_day=self.START, end_day=self.END, frequency=Tenor("3M"))

    def _fix_of(self, day):
        return FixOf("LIBOR", day, "3M")

    def test_two_period_lifecycle_on_ledger(self):
        from corda_tpu.contracts.universal import actions_of

        l = ledger(NOTARY)
        # --- period 1: apply the oracle fixing (LIBOR = 1.0%)
        fixes1 = {self._fix_of(self.START): SCALE}
        fixed1 = replace_fixings(reduce_rollout(self.swap), fixes1)
        with l.transaction() as tx:
            tx.input(ustate(self.swap))
            tx.output("fixed-1", ustate(fixed1))
            tx.command(UApplyFixes((Fix(self._fix_of(self.START), SCALE),)),
                       ACME.owning_key)
            tx.command(Fix(self._fix_of(self.START), SCALE),
                       MOMENTUM.owning_key)
            tx.verifies()

        # --- period 1: floating (1.0%) > fixed (0.5%): HighSt pays the net
        action = actions_of(fixed1)["settle"]
        parts = set(action.arrangement.arrangements)
        pays = [p for p in parts if isinstance(p, Transfer)]
        rest = next(p for p in parts if isinstance(p, RollOut))
        to_acme = next(p for p in pays if p.to_party == ACME)
        to_highst = next(p for p in pays if p.to_party == HIGH_ST)
        net = eval_amount(None, to_acme.amount)
        days1 = rest.start_day - self.START
        assert net == (to_quanta(50_000_000) * (SCALE // 2) * days1) \
            // (100 * SCALE * 365) > 0
        assert eval_amount(None, to_highst.amount) == 0
        settled = Transfer(Const(net), "EUR", HIGH_ST, ACME)
        zero_leg = Transfer(Const(0), "EUR", ACME, HIGH_ST)
        with l.transaction() as tx:
            tx.input("fixed-1")
            tx.output("settled-1", ustate(settled))
            tx.output(None, ustate(zero_leg))
            tx.output("rest", ustate(rest))
            tx.command(UAction("settle"), HIGH_ST.owning_key)
            tx.timestamp(day_ts(rest.start_day))
            # the debtor cannot discharge the period while omitting the net
            # payment: output must carry BOTH evaluated legs
            with tx.tweak() as tw:
                tw.outputs = [o for o in tw.outputs
                              if o[1].details != settled]
                tw.fails_with("match action result")
            tx.verifies()

        # the rolled remainder still owns its placeholders (inner scope)
        assert rest.template == self.swap.template

        # --- period 2: the remaining schedule fixes independently
        fixes2 = {self._fix_of(rest.start_day): SCALE // 4}  # 0.25%
        fixed2 = replace_fixings(reduce_rollout(rest), fixes2)
        with l.transaction() as tx:
            tx.input("rest")
            tx.output("fixed-2", ustate(fixed2))
            tx.command(
                UApplyFixes((Fix(self._fix_of(rest.start_day), SCALE // 4),)),
                ACME.owning_key)
            tx.command(Fix(self._fix_of(rest.start_day), SCALE // 4),
                       MOMENTUM.owning_key)
            tx.verifies()

        # period 2: fixed (0.5%) > floating (0.25%): the net now flows the
        # other way — ACME pays HighSt — out of the same single settle action
        action2 = actions_of(fixed2)["settle"]
        pays2 = [p for p in set(action2.arrangement.arrangements)
                 if isinstance(p, Transfer)]
        to_highst2 = next(p for p in pays2 if p.to_party == HIGH_ST)
        to_acme2 = next(p for p in pays2 if p.to_party == ACME)
        assert eval_amount(None, to_highst2.amount) > 0
        assert eval_amount(None, to_acme2.amount) == 0

    def test_fixing_with_wrong_oracle_rejected_for_irs(self):
        fixes = {self._fix_of(self.START): SCALE}
        fixed = replace_fixings(reduce_rollout(self.swap), fixes)
        l = ledger(NOTARY)
        with l.transaction() as tx:
            tx.input(ustate(self.swap))
            tx.output(None, ustate(fixed))
            tx.command(UApplyFixes((Fix(self._fix_of(self.START), SCALE),)),
                       ACME.owning_key)
            tx.command(Fix(self._fix_of(self.START), SCALE), ACME.owning_key)
            tx.fails_with("attested")


class TestRollOut:
    """reference: RollOutTests.kt — schedules expand one period at a time."""

    def setup_method(self):
        start = date_to_days(dt.date(2017, 1, 2))  # a Monday
        end = date_to_days(dt.date(2017, 4, 3))
        template = actions(
            arrange("pay", after(EndDate()), ACME,
                    all_of(
                        transfer(Interest(Const(to_quanta(1_000_000)),
                                          "ACT/360", Const(5 * SCALE),
                                          StartDate(), EndDate()),
                                 "USD", HIGH_ST, ACME),
                        Continuation())))
        self.roll = RollOut(start, end, Tenor("1M"), template)

    def test_reduce_substitutes_period_and_continuation(self):
        reduced = reduce_rollout(self.roll)
        assert isinstance(reduced, Actions)
        action = next(iter(reduced.actions))
        assert isinstance(action.arrangement, All)
        parts = set(action.arrangement.arrangements)
        rolls = [p for p in parts if isinstance(p, RollOut)]
        pays = [p for p in parts if isinstance(p, Transfer)]
        assert len(rolls) == 1 and len(pays) == 1
        assert rolls[0].start_day > self.roll.start_day
        assert rolls[0].end_day == self.roll.end_day
        # period dates were substituted into the transfer amount
        assert isinstance(pays[0].amount, Interest)
        assert pays[0].amount.start == Const(self.roll.start_day)

    def test_final_period_drops_continuation(self):
        short = RollOut(self.roll.start_day,
                        self.roll.start_day + 20, Tenor("1M"),
                        self.roll.template)
        reduced = reduce_rollout(short)
        action = next(iter(reduced.actions))
        assert isinstance(action.arrangement, Transfer)  # no Continuation left

    def test_exercise_rolled_period_on_ledger(self):
        reduced = reduce_rollout(self.roll)
        action = next(iter(reduced.actions))
        period_end = action.arrangement and None
        # Build the expected settled output: evaluate the transfer, keep rest.
        l = ledger(NOTARY)
        end_day = next(p for p in action.arrangement.arrangements
                       if isinstance(p, RollOut)).start_day
        interest_amount = (to_quanta(1_000_000) * 5 * SCALE
                           * (end_day - self.roll.start_day)) \
            // (100 * SCALE * 360)
        settled = all_of(
            Transfer(Const(interest_amount), "USD", HIGH_ST, ACME),
            next(p for p in action.arrangement.arrangements
                 if isinstance(p, RollOut)))
        with l.transaction() as tx:
            tx.input(ustate(self.roll))
            tx.output(None, ustate(settled))
            tx.command(UAction("pay"), ACME.owning_key)
            tx.timestamp(day_ts(end_day))
            tx.verifies()


def test_multiset_equal_is_order_and_repr_independent():
    # Round-3 advisor: sorted(key=repr) misaligned equal multisets when
    # equal Arrangement values holding frozenset fields repr'd their
    # elements in different orders. The matcher must use only __eq__.
    from corda_tpu.contracts.universal import _multiset_equal

    class OrderlessRepr:
        """Equal values that repr differently (models frozenset fields)."""

        def __init__(self, key, salt):
            self.key = key
            self.salt = salt

        def __eq__(self, other):
            return isinstance(other, OrderlessRepr) and self.key == other.key

        def __repr__(self):  # pragma: no cover - diagnostic only
            return f"OrderlessRepr({self.salt!r})"

    a1, a2 = OrderlessRepr("a", "x"), OrderlessRepr("a", "y")
    b = OrderlessRepr("b", "z")
    assert _multiset_equal([a1, b], [b, a2])      # order + repr independent
    assert not _multiset_equal([a1, a2, b], [a1, b])   # duplicate minted
    assert not _multiset_equal([a1], [a1, b])          # part missing
    assert _multiset_equal([], [])
