"""Finance types, interpolators, graph search, generators, Expect DSL.

Mirrors the reference's coverage of FinanceTypes (reference: core/src/test/
kotlin/net/corda/core/contracts/FinanceTypesTest.kt), Interpolators
(core/.../math/InterpolatorsTest.kt), TransactionGraphSearch
(core/.../contracts/TransactionGraphSearchTests.kt) and the Expect DSL.
"""

import datetime
import random

import pytest

from corda_tpu.finance.types import (
    BusinessCalendar,
    FOLLOWING,
    MODIFIED_FOLLOWING,
    PREVIOUS,
    Tenor,
    date_to_days,
    days_to_date,
)
from corda_tpu.utils.interpolators import (
    CubicSplineInterpolator,
    LinearInterpolator,
)


class TestTenorCalendar:
    def test_tenor_parse_and_advance(self):
        start = date_to_days(datetime.date(2026, 1, 30))
        assert Tenor("5D").days_from(start) == 5
        assert Tenor("2W").days_from(start) == 14
        # Month arithmetic clamps to month end: Jan 30 + 1M -> Feb 28.
        assert days_to_date(start + Tenor("1M").days_from(start)) \
            == datetime.date(2026, 2, 28)
        assert days_to_date(start + Tenor("1Y").days_from(start)) \
            == datetime.date(2027, 1, 30)
        with pytest.raises(ValueError):
            Tenor("3Q")

    def test_frequency_offsets(self):
        from corda_tpu.finance.types import Frequency

        start = date_to_days(datetime.date(2016, 9, 1))
        assert days_to_date(Frequency.QUARTERLY.offset(start)) \
            == datetime.date(2016, 12, 1)
        assert days_to_date(Frequency.QUARTERLY.offset(start, n=2)) \
            == datetime.date(2017, 3, 1)
        assert days_to_date(Frequency.ANNUAL.offset(start)) \
            == datetime.date(2017, 9, 1)
        assert Frequency.of("SemiAnnual").annual_compound_count == 2
        assert Frequency.MONTHLY.tenor == Tenor("1M")

    def test_roll_conventions(self):
        sat = date_to_days(datetime.date(2026, 1, 31))  # Saturday
        cal = BusinessCalendar()
        assert days_to_date(cal.roll(sat, FOLLOWING)) \
            == datetime.date(2026, 2, 2)  # Monday
        assert days_to_date(cal.roll(sat, PREVIOUS)) \
            == datetime.date(2026, 1, 30)  # Friday
        # ModifiedFollowing bounces back when following crosses month end.
        assert days_to_date(cal.roll(sat, MODIFIED_FOLLOWING)) \
            == datetime.date(2026, 1, 30)

    def test_holidays_and_union(self):
        friday = date_to_days(datetime.date(2026, 2, 6))
        cal = BusinessCalendar(frozenset({friday}))
        assert not cal.is_working_day(friday)
        assert days_to_date(cal.roll(friday, FOLLOWING)) \
            == datetime.date(2026, 2, 9)
        merged = BusinessCalendar.union(cal, BusinessCalendar())
        assert friday in merged.holidays


class TestInterpolators:
    def test_linear(self):
        li = LinearInterpolator((0.0, 10.0), (0.0, 100.0))
        assert li.interpolate(5.0) == 50.0
        with pytest.raises(ValueError):
            li.interpolate(11.0)

    def test_cubic_spline_passes_through_knots_and_is_smooth(self):
        xs = (0.0, 1.0, 2.0, 3.0, 4.0)
        ys = (1.0, 2.0, 0.5, 3.0, 2.5)
        cs = CubicSplineInterpolator(xs, ys)
        for x, y in zip(xs, ys):
            assert abs(cs.interpolate(x) - y) < 1e-9
        # Between knots the spline stays bounded (no wild oscillation).
        samples = [cs.interpolate(x / 10) for x in range(0, 41)]
        assert all(-2.0 < s < 5.0 for s in samples)


class TestGraphSearch:
    def test_finds_issuance_in_ancestry(self):
        from corda_tpu.crypto.keys import KeyPair
        from corda_tpu.crypto.party import Party
        from corda_tpu.testing.dummies import DummyContract, DummyCreate
        from corda_tpu.transactions.graph_search import (
            Query,
            TransactionGraphSearch,
        )

        class MemStorage:
            def __init__(self):
                self.txs = {}

            def add(self, stx):
                self.txs[stx.id] = stx

            def get_transaction(self, h):
                return self.txs.get(h)

        alice_key = KeyPair.generate(b"\x51" * 32)
        alice = Party.of("Alice", alice_key.public)
        notary = Party.of("Notary", KeyPair.generate(b"\x52" * 32).public)
        storage = MemStorage()

        issue = DummyContract.generate_initial(alice.ref(b"\x01"), 1, notary)
        issue.sign_with(alice_key)
        issue_stx = issue.to_signed_transaction()
        storage.add(issue_stx)

        move = DummyContract.move(issue_stx.tx.out_ref(0), alice.owning_key)
        move.sign_with(alice_key)
        move_stx = move.to_signed_transaction(check_sufficient_signatures=False)
        storage.add(move_stx)

        found = TransactionGraphSearch(storage, [move_stx.tx]).run(
            Query(with_command_of_type=DummyCreate))
        assert [w.id for w in found] == [issue_stx.id]
        assert TransactionGraphSearch(storage, [move_stx.tx]).run(
            Query(with_command_of_type=int)) == []


class TestGenerators:
    def test_generator_monad_composes(self):
        from corda_tpu.testing.generators import Generator

        rng = random.Random(42)
        gen = Generator.int_range(1, 6).flat_map(
            lambda n: Generator.pick(["a", "b"]).map(lambda s: s * n))
        values = gen.list_of(20).generate(rng)
        assert all(set(v) <= {"a", "b"} and 1 <= len(v) <= 6 for v in values)

    def test_cash_event_stream_stays_valid(self):
        from corda_tpu.testing.generators import (
            ExitEvent,
            IssueEvent,
            MoveEvent,
            cash_event_generator,
        )

        rng = random.Random(7)
        balance = {"issued": 0}
        gen = cash_event_generator(["alice", "bob"],
                                   lambda: balance["issued"])
        for _ in range(200):
            event = gen.generate(rng)
            if isinstance(event, IssueEvent):
                balance["issued"] += event.amount.quantity
            elif isinstance(event, (MoveEvent, ExitEvent)):
                # Never exceeds what exists.
                assert event.amount.quantity <= balance["issued"]
                if isinstance(event, ExitEvent):
                    balance["issued"] -= event.amount.quantity


class TestExpectDsl:
    def test_sequence_and_parallel(self):
        from corda_tpu.testing.expect import (
            ExpectationFailed,
            expect,
            expect_events,
            parallel,
            sequence,
        )

        class A:
            def __init__(self, n):
                self.n = n

        class B:
            pass

        feed = [A(1), B(), A(2), B()]
        expect_events(feed, sequence(
            expect(A, lambda e: e.n == 1),
            parallel(expect(A, lambda e: e.n == 2), expect(B)),
            expect(B),
        ))
        with pytest.raises(ExpectationFailed):
            expect_events([A(1)], sequence(expect(A), expect(B)))


class TestSimulation:
    def test_trade_simulation_over_latency_network(self):
        """TradeSimulation (irs-demo Simulation.kt capability): a DvP trade
        completes over a latency-injected WAN-shaped network, and the
        sent-message feed (the network-visualiser's input) records the
        conversation."""
        from corda_tpu.finance import CashState
        from corda_tpu.testing.simulation import TradeSimulation

        sim = TradeSimulation()
        try:
            final = sim.run_trade(price_quantity=750)
            seller, buyer = sim.banks
            paid = sum(o.data.amount.quantity for o in final.tx.outputs
                       if isinstance(o.data, CashState)
                       and o.data.owner == seller.identity.owning_key)
            assert paid == 750
            # The visualiser feed saw a real multi-party conversation.
            assert len(sim.sent_messages) >= 6
            senders = {m.sender for m in sim.sent_messages}
            assert len(senders) >= 3  # both banks and the notary spoke
        finally:
            sim.stop()


class TestSmallUtils:
    def test_non_empty_set(self):
        from corda_tpu.utils.collections import NonEmptySet

        s = NonEmptySet.of(1, 2, 3)
        assert 2 in s and len(s) == 3
        with pytest.raises(ValueError):
            NonEmptySet([])
        with pytest.raises(ValueError):
            s - {1, 2, 3}
        assert s & {2, 3} == {2, 3}

    def test_progress_renderer_follows_feed(self, tmp_path):
        import io

        from corda_tpu.node.config import NodeConfig
        from corda_tpu.node.node import Node
        from corda_tpu.utils.progress import ProgressTracker, Step
        from corda_tpu.utils.progress_render import ProgressRenderer
        from corda_tpu.flows.api import FlowLogic, register_flow

        @register_flow
        class SteppyFlow(FlowLogic):
            def __init__(self, n: int):
                self.n = n
                self.progress_tracker = ProgressTracker(
                    Step("Working"), Step("Finishing"))

            def call(self):
                self.progress_tracker.next_step()
                self.progress_tracker.next_step()
                return self.n

        node = Node(NodeConfig(name="P", base_dir=tmp_path / "P",
                               network_map=tmp_path / "m.json")).start()
        try:
            out = io.StringIO()
            renderer = ProgressRenderer(node.smm, out=out)
            node.start_flow(SteppyFlow(1))
            lines = renderer.poll()
            text = "\n".join(lines)
            assert "started" in text and "Working" in text \
                and "Finishing" in text and "finished" in text
        finally:
            node.stop()

    def test_cash_balance_metrics(self, tmp_path):
        from corda_tpu.finance import Amount, Cash
        from corda_tpu.node.config import NodeConfig
        from corda_tpu.node.node import Node

        node = Node(NodeConfig(name="B", base_dir=tmp_path / "B",
                               network_map=tmp_path / "m.json")).start()
        try:
            issue = Cash.generate_issue(
                Amount(1234, "USD"), node.identity.ref(b"\x01"),
                node.identity.owning_key, node.identity)
            issue.sign_with(node.key)
            node.services.record_transactions([issue.to_signed_transaction()])
            assert node.smm.metrics["balance.USD"] == 1234
        finally:
            node.stop()
