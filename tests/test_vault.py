"""Indexed vault plane (round 22): engine parity, soft-locked coin
selection, keyset pagination stability, watermark incremental boot, and
the doctor/gate/autotune plumbing that steers operators onto it.

The two engines — in-memory NodeVaultService and sqlite
IndexedVaultService — must answer the same notify/query/select surface
identically; these tests pin that contract from both sides of the
``[vault] indexed`` switch.
"""

import threading
import time

from corda_tpu.contracts.structures import (
    Issued,
    StateAndRef,
    StateRef,
    TransactionState,
)
from corda_tpu.crypto.hashes import SecureHash
from corda_tpu.crypto.party import PartyAndReference
from corda_tpu.finance.amount import Amount
from corda_tpu.finance.cash import CashState
from corda_tpu.node.config import NodeConfig, VaultConfig
from corda_tpu.node.services.inmemory import NodeVaultService
from corda_tpu.node.services.persistence import NodeDatabase
from corda_tpu.node.services.vault import (
    IndexedVaultService,
    SoftLockManager,
    VaultQuery,
    seed_states,
)
from corda_tpu.obs import doctor
from corda_tpu.obs import telemetry as _tm
from corda_tpu.serialization.codec import serialize
from corda_tpu.testing.identities import ALICE, BOB, DUMMY_NOTARY, MEGA_CORP
from corda_tpu.utils.bytes import OpaqueBytes

USD = Issued(PartyAndReference(MEGA_CORP, OpaqueBytes(b"\x01")), "USD")
EUR = Issued(PartyAndReference(MEGA_CORP, OpaqueBytes(b"\x01")), "EUR")


def _our_keys():
    return set(ALICE.owning_key.keys) | set(BOB.owning_key.keys)


def _tx_hash(i: int) -> SecureHash:
    return SecureHash(i.to_bytes(16, "big") + b"vault-test-pad!!")


def _cash(qty: int, token=USD, owner=None) -> TransactionState:
    return TransactionState(
        CashState(Amount(qty, token), owner or ALICE.owning_key),
        DUMMY_NOTARY)


class _SeedTx:
    """Signed-tx shim: .tx/.id/inputs/outputs/out_ref — everything
    notify_all touches, none of the signing/Merkle machinery."""

    __slots__ = ("id", "inputs", "outputs")

    def __init__(self, id, outputs, inputs=()):
        self.id = id
        self.outputs = tuple(outputs)
        self.inputs = tuple(inputs)

    @property
    def tx(self):
        return self

    def out_ref(self, i):
        return StateAndRef(self.outputs[i], StateRef(self.id, i))


class _SeedStorage:
    """stream_since twin over an in-memory tx list whose position
    mirrors the transactions-table rowid (rows inserted in order)."""

    def __init__(self, txs):
        self._txs = list(txs)

    def stream_since(self, after_rowid=0, batch=512):
        start = int(after_rowid)
        for i, stx in enumerate(self._txs[start:], start=start + 1):
            yield i, stx


def _indexed(tmp_path, name="vault.db", **kw):
    db = NodeDatabase(tmp_path / name)
    return db, IndexedVaultService(db, _our_keys, **kw)


def _snapshot(engine):
    return sorted(
        (s.ref.txhash.bytes, s.ref.index, serialize(s.state).bytes)
        for s in engine.iter_unconsumed())


def _issue_stream(n, qty=lambda i: 100 + i):
    return [_SeedTx(_tx_hash(i), (_cash(qty(i)),)) for i in range(n)]


# ---------------------------------------------------------------------------
# Engine parity
# ---------------------------------------------------------------------------


class TestEngineParity:
    def test_identical_unconsumed_set_after_issue_and_spend(self, tmp_path):
        issues = _issue_stream(40)
        spends = [
            _SeedTx(_tx_hash(100 + k), (_cash(7 + k),),
                    inputs=(StateRef(_tx_hash(i), 0),))
            for k, i in enumerate(range(0, 40, 3))]
        mem = NodeVaultService(_our_keys)
        db, idx = _indexed(tmp_path)
        for engine in (mem, idx):
            engine.notify_all(issues)
            engine.notify_all(spends)
        assert _snapshot(mem) == _snapshot(idx)
        assert mem.balances() == idx.balances()
        db.close()

    def test_query_pushdowns_agree(self, tmp_path):
        txs = [_SeedTx(_tx_hash(i), (
            _cash(100 + i, USD if i % 2 else EUR,
                  ALICE.owning_key if i % 3 else BOB.owning_key),))
            for i in range(30)]
        mem = NodeVaultService(_our_keys)
        db, idx = _indexed(tmp_path)
        for engine in (mem, idx):
            engine.notify_all(txs)
        for q in (VaultQuery(currency="USD"),
                  VaultQuery(currency="EUR", min_amount=110),
                  VaultQuery(min_amount=105, max_amount=120),
                  VaultQuery(participant=BOB.owning_key),
                  VaultQuery(state_type=CashState)):
            a = [s.ref for s in mem.query(q).states]
            b = [s.ref for s in idx.query(q).states]
            assert a == b, q
        db.close()

    def test_pagination_cursors_mean_the_same_thing(self, tmp_path):
        txs = _issue_stream(25)
        mem = NodeVaultService(_our_keys)
        db, idx = _indexed(tmp_path)
        for engine in (mem, idx):
            engine.notify_all(txs)

        def walk(engine):
            cursor, refs, pages = None, [], 0
            while True:
                page = engine.query(VaultQuery(after=cursor, page_size=7))
                refs.extend(s.ref for s in page.states)
                pages += 1
                cursor = page.next_cursor
                if cursor is None:
                    return refs, pages

        a, pa = walk(mem)
        b, pb = walk(idx)
        assert a == b and len(a) == 25
        assert pa == pb == 4
        db.close()

    def test_coin_selection_picks_same_coins(self, tmp_path):
        txs = _issue_stream(10, qty=lambda i: 50 * (i + 1))
        mem = NodeVaultService(_our_keys)
        db, idx = _indexed(tmp_path)
        for engine in (mem, idx):
            engine.notify_all(txs)
        a = [s.ref for s in mem.select_coins("USD", 900, holder=b"a")]
        b = [s.ref for s in idx.select_coins("USD", 900, holder=b"a")]
        assert a == b and a  # largest-first on both engines
        db.close()

    def test_unconsumed_states_shim_matches_current_vault(self, tmp_path):
        db, idx = _indexed(tmp_path)
        idx.notify_all(_issue_stream(5))
        assert [s.ref for s in idx.unconsumed_states()] == \
            [s.ref for s in idx.current_vault.states]
        assert [s.ref for s in idx.unconsumed_states(CashState)] == \
            [s.ref for s in idx.unconsumed_states()]
        assert len(idx) == 5
        db.close()


def test_inmemory_typed_index_matches_global_scan_order():
    """The per-type secondary index must return the exact subsequence
    the old isinstance full scan produced."""
    mem = NodeVaultService(_our_keys)
    mem.notify_all(_issue_stream(12))
    by_index = [s.ref for s in mem.iter_unconsumed(CashState)]
    by_scan = [s.ref for s in mem.current_vault.states
               if isinstance(s.state.data, CashState)]
    assert by_index == by_scan
    # Consumption maintains the bucket.
    mem.notify_all([_SeedTx(_tx_hash(50), (),
                            inputs=(StateRef(_tx_hash(0), 0),))])
    assert len(list(mem.iter_unconsumed(CashState))) == 11


# ---------------------------------------------------------------------------
# Soft-locked coin selection
# ---------------------------------------------------------------------------


class TestSoftLocks:
    def test_one_coin_exactly_one_winner(self, tmp_path):
        _tm.arm()
        db, idx = _indexed(tmp_path)
        idx.notify_all([_SeedTx(_tx_hash(0), (_cash(100),))])
        a = idx.select_coins("USD", 100, holder=b"flow-a")
        b = idx.select_coins("USD", 100, holder=b"flow-b")
        assert len(a) == 1 and b == []
        assert _tm.ACTIVE.counter(
            "vault_selection_conflicts_total").value >= 1
        db.close()

    def test_loser_retries_onto_a_different_coin(self, tmp_path):
        db, idx = _indexed(tmp_path)
        idx.notify_all(_issue_stream(2, qty=lambda i: 100))
        a = idx.select_coins("USD", 100, holder=b"flow-a")
        b = idx.select_coins("USD", 100, holder=b"flow-b")
        assert len(a) == 1 and len(b) == 1
        assert a[0].ref != b[0].ref
        db.close()

    def test_ttl_expiry_readmits_the_coin(self, tmp_path):
        _tm.arm()
        db, idx = _indexed(tmp_path, softlock_ttl_s=0.02)
        idx.notify_all([_SeedTx(_tx_hash(0), (_cash(100),))])
        a = idx.select_coins("USD", 100, holder=b"crashed-flow")
        assert len(a) == 1
        time.sleep(0.05)
        b = idx.select_coins("USD", 100, holder=b"flow-b")
        assert [s.ref for s in b] == [s.ref for s in a]
        assert _tm.ACTIVE.counter(
            "vault_softlock_expired_total").value >= 1
        db.close()

    def test_consumption_releases_the_lock(self, tmp_path):
        db, idx = _indexed(tmp_path)
        idx.notify_all([_SeedTx(_tx_hash(0), (_cash(100),))])
        (coin,) = idx.select_coins("USD", 100, holder=b"flow-a")
        idx.notify_all([_SeedTx(_tx_hash(1), (), inputs=(coin.ref,))])
        assert len(idx.softlocks) == 0
        db.close()

    def test_insufficient_funds_releases_partial_reservation(self, tmp_path):
        db, idx = _indexed(tmp_path)
        idx.notify_all([_SeedTx(_tx_hash(0), (_cash(100),))])
        got = idx.select_coins("USD", 500, holder=b"flow-a")
        assert len(got) == 1  # the partial set, for the asset's error path
        assert len(idx.softlocks) == 0  # but nothing stays shadowed
        db.close()

    def test_concurrent_selection_never_double_selects(self, tmp_path):
        db, idx = _indexed(tmp_path)
        idx.notify_all(_issue_stream(8, qty=lambda i: 100))
        picked, errors = [], []

        def worker(name):
            try:
                picked.append((name,
                               idx.select_coins("USD", 100, holder=name)))
            except Exception as e:  # surfaced below; threads must not die
                errors.append(e)

        threads = [threading.Thread(target=worker,
                                    args=(b"flow-%d" % i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        refs = [c.ref for _name, coins in picked for c in coins]
        assert len(refs) == len(set(refs)) == 8  # exactly-once, all served
        db.close()

    def test_softlock_manager_relock_refreshes_own_ttl(self):
        locks = SoftLockManager(ttl_s=10.0)
        ref = StateRef(_tx_hash(0), 0)
        assert locks.try_lock(ref, b"a", now=0.0)
        assert not locks.try_lock(ref, b"b", now=1.0)
        assert locks.try_lock(ref, b"a", now=9.0)  # refresh
        assert not locks.try_lock(ref, b"b", now=15.0)  # still held
        assert locks.try_lock(ref, b"b", now=25.0)  # expired


# ---------------------------------------------------------------------------
# Keyset pagination under concurrent consumption
# ---------------------------------------------------------------------------


def test_keyset_pagination_stable_under_consumption(tmp_path):
    db, idx = _indexed(tmp_path)
    idx.notify_all(_issue_stream(60))
    first = idx.query(VaultQuery(page_size=20))
    seen = [s.ref for s in first.states]
    # Consume states BOTH behind the cursor (already paged) and ahead of
    # it: an OFFSET pager would shift and either skip or repeat rows.
    behind = seen[:5]
    ordered = sorted((s.ref for s in idx.iter_unconsumed()),
                     key=lambda r: (r.txhash.bytes, r.index))
    ahead = [r for r in ordered if r not in set(seen)][:5]
    idx.notify_all([_SeedTx(_tx_hash(200), (),
                            inputs=tuple(behind + ahead))])
    cursor = first.next_cursor
    while cursor is not None:
        page = idx.query(VaultQuery(after=cursor, page_size=20))
        seen.extend(s.ref for s in page.states)
        cursor = page.next_cursor
    assert len(seen) == len(set(seen))  # no duplicates despite churn
    # Every state is accounted for: paged, or consumed ahead of paging.
    assert set(seen) | set(ahead) == {
        StateRef(_tx_hash(i), 0) for i in range(60)}
    db.close()


# ---------------------------------------------------------------------------
# Watermark incremental boot
# ---------------------------------------------------------------------------


class TestIncrementalBoot:
    def _ledger(self, db, n, start=0):
        txs = [_SeedTx(_tx_hash(i), (_cash(100 + i),))
               for i in range(start, n)]
        with db.lock:
            db.conn.executemany(
                "INSERT INTO transactions (tx_id, blob) VALUES (?, ?)",
                ((stx.id.bytes, b"") for stx in txs))
            db.commit()
        return txs

    def test_restart_replays_only_the_delta(self, tmp_path):
        db = NodeDatabase(tmp_path / "boot.db")
        txs = self._ledger(db, 20)
        vault = IndexedVaultService(db, _our_keys)
        assert vault.rebuild_from(_SeedStorage(txs), batch=8) == 20
        assert vault.watermark == 20
        # New transactions land while the vault engine is "down".
        txs += self._ledger(db, 25, start=20)
        reborn = IndexedVaultService(db, _our_keys)
        assert reborn.rebuild_from(_SeedStorage(txs), batch=8) == 5
        assert reborn.watermark == 25
        assert len(reborn) == 25
        # A current store replays nothing at all.
        assert IndexedVaultService(db, _our_keys).rebuild_from(
            _SeedStorage(txs)) == 0
        db.close()

    def test_crash_replay_is_idempotent_and_silent(self, tmp_path):
        """Re-folding already-applied transactions (the crash-between-
        watermark-batches shape) must not double-count balances or
        re-fire observers."""
        db = NodeDatabase(tmp_path / "boot.db")
        txs = self._ledger(db, 10)
        vault = IndexedVaultService(db, _our_keys)
        vault.rebuild_from(_SeedStorage(txs))
        balances = vault.balances()
        fired = []
        vault.subscribe(lambda update: fired.append(update))
        vault.notify_all(txs)  # the whole prefix again
        assert vault.balances() == balances
        assert fired == []
        assert len(vault) == 10
        db.close()

    def test_spends_replay_cleanly_through_the_watermark(self, tmp_path):
        db = NodeDatabase(tmp_path / "boot.db")
        issues = self._ledger(db, 10)
        spend = _SeedTx(_tx_hash(100), (_cash(1),),
                        inputs=(StateRef(_tx_hash(0), 0),
                                StateRef(_tx_hash(1), 0)))
        with db.lock:
            db.conn.execute(
                "INSERT INTO transactions (tx_id, blob) VALUES (?, ?)",
                (spend.id.bytes, b""))
            db.commit()
        txs = issues + [spend]
        vault = IndexedVaultService(db, _our_keys)
        vault.rebuild_from(_SeedStorage(txs))
        assert vault.watermark == 11
        assert len(vault) == 9  # 10 issued - 2 consumed + 1 change
        expect = vault.balances()
        reborn = IndexedVaultService(db, _our_keys)
        assert reborn.rebuild_from(_SeedStorage(txs)) == 0
        assert reborn.balances() == expect
        db.close()


# ---------------------------------------------------------------------------
# Durability: bitrot becomes a repair event, never a wrong answer
# ---------------------------------------------------------------------------


def test_corrupt_vault_row_is_quarantined(tmp_path):
    db, idx = _indexed(tmp_path)
    idx.notify_all(_issue_stream(3))
    with db.lock:
        db.conn.execute(
            "UPDATE vault_states SET blob = substr(blob, 2) "
            "WHERE ref_txhash = ?", (_tx_hash(1).bytes,))
        db.commit()
    survivors = [s.ref for s in idx.unconsumed_states()]
    assert StateRef(_tx_hash(1), 0) not in survivors
    assert len(survivors) == 2
    (n,) = db.conn.execute(
        "SELECT COUNT(*) FROM quarantine WHERE kind = 'vault_state'"
    ).fetchone()
    assert n == 1
    db.close()


# ---------------------------------------------------------------------------
# Config / node plumbing
# ---------------------------------------------------------------------------


class TestConfigPlumbing:
    def test_vault_config_defaults_and_parse(self):
        assert VaultConfig().indexed is False
        cfg = NodeConfig.from_dict({
            "name": "V", "base_dir": "/tmp/v",
            "vault": {"indexed": True, "softlock_ttl_s": 2.5,
                      "rebuild_batch": 64}})
        assert cfg.vault.indexed is True
        assert cfg.vault.softlock_ttl_s == 2.5
        assert cfg.vault.rebuild_batch == 64

    def test_node_arms_indexed_engine_from_config(self, tmp_path):
        from corda_tpu.node.node import Node
        node = Node(NodeConfig(
            name="Ix", base_dir=tmp_path / "Ix",
            network_map=tmp_path / "netmap.json",
            vault=VaultConfig(indexed=True))).start()
        try:
            assert isinstance(node.services.vault_service,
                              IndexedVaultService)
        finally:
            node.stop()

    def test_node_defaults_to_inmemory_engine(self, tmp_path):
        from corda_tpu.node.node import Node
        node = Node(NodeConfig(
            name="Mem", base_dir=tmp_path / "Mem",
            network_map=tmp_path / "netmap.json")).start()
        try:
            assert isinstance(node.services.vault_service,
                              NodeVaultService)
        finally:
            node.stop()

    def test_env_var_arms_indexed_engine(self, tmp_path, monkeypatch):
        from corda_tpu.node.node import Node
        monkeypatch.setenv("CORDA_TPU_VAULT_INDEXED", "1")
        node = Node(NodeConfig(
            name="Env", base_dir=tmp_path / "Env",
            network_map=tmp_path / "netmap.json")).start()
        try:
            assert isinstance(node.services.vault_service,
                              IndexedVaultService)
        finally:
            node.stop()

    def test_indexed_vault_survives_restart(self, tmp_path):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from test_tcp_node import issue_and_move

        from corda_tpu.node.node import Node
        cfg = lambda: NodeConfig(  # noqa: E731
            name="VX", base_dir=tmp_path / "VX",
            network_map=tmp_path / "netmap.json",
            vault=VaultConfig(indexed=True))
        node = Node(cfg()).start()
        stx = issue_and_move(node, node.identity, magic=5)
        node.services.record_transactions([stx])
        before = _snapshot(node.services.vault_service)
        assert before
        node.stop()
        del node

        reborn = Node(cfg()).start()
        try:
            assert _snapshot(reborn.services.vault_service) == before
        finally:
            reborn.stop()

    def test_autotune_knob_resolves_and_overlays(self):
        from corda_tpu.autotune import space
        assert space.validate_registry() == []
        assert space.overlay_for({"vault.indexed": 1}) == {
            "vault": {"indexed": 1}}


# ---------------------------------------------------------------------------
# Doctor: the vault_scan rule and the vault_scaling gate keys
# ---------------------------------------------------------------------------


def _breakdown_artifact(vault_share, traces=40):
    e2e = 100.0
    return {
        "metric": "verified_sigs_per_sec",
        "baseline_configs": {
            "raft_open_loop_latency": {
                "stage_breakdown": {
                    "traces": traces,
                    "end_to_end": {"mean_ms": e2e},
                    "stages": {
                        "vault_query": {"mean_ms": e2e * vault_share},
                        "verify_wait": {"mean_ms": 5.0},
                    },
                }}}}


class TestDoctorVaultScan:
    def test_rule_fires_on_dominant_vault_share(self):
        signals = doctor.extract_signals(_breakdown_artifact(0.4))
        assert signals["flow_stage_shares"]["vault_query"] == 0.4
        verdict = doctor.diagnose(signals)
        hit = next(b for b in verdict["bottlenecks"]
                   if b["cause"] == "vault_scan")
        assert hit["score"] == 0.7
        assert hit["experiment"]["experiment_id"] == "arm_indexed_vault"
        assert "vault.indexed" in hit["experiment"]["knobs"]
        assert "indexed=true" in hit["next_experiment"]

    def test_rule_abstains_below_threshold_share(self):
        verdict = doctor.diagnose(
            doctor.extract_signals(_breakdown_artifact(0.1)))
        assert all(b["cause"] != "vault_scan"
                   for b in verdict["bottlenecks"])

    def test_rule_abstains_below_min_traces(self):
        signals = doctor.extract_signals(_breakdown_artifact(0.9, traces=5))
        assert "flow_stage_shares" not in signals

    def test_gate_hoists_vault_metrics_and_fails_on_parity_flip(self):
        def artifact(ratio, parity):
            return {
                "metric": "verified_sigs_per_sec",
                "baseline_configs": {"vault_scaling": {
                    "vault_coin_selection_p99_ratio": ratio,
                    "vault_boot_speedup": 40.0,
                    "vault_query_p99_ms": 12.0,
                    "vault_parity_ok": parity,
                }}}
        prev = doctor.normalize_record(artifact(2.0, True), source="r22_a")
        assert prev["metrics"]["vault_parity_ok"] is True
        assert prev["metrics"]["vault_coin_selection_p99_ratio"] == 2.0
        ok = doctor.gate([prev,
                          doctor.normalize_record(artifact(2.1, True),
                                                  source="r22_b")])
        assert ok["ok"]
        flipped = doctor.gate([prev,
                               doctor.normalize_record(artifact(2.0, False),
                                                       source="r22_c")])
        assert not flipped["ok"]
        assert any(r["metric"] == "vault_parity_ok"
                   for r in flipped["regressions"])
        regressed = doctor.gate([prev,
                                 doctor.normalize_record(artifact(3.0, True),
                                                         source="r22_d")])
        assert not regressed["ok"]
        assert any(r["metric"] == "vault_coin_selection_p99_ratio"
                   for r in regressed["regressions"])


# ---------------------------------------------------------------------------
# The bench section, end to end at toy scale
# ---------------------------------------------------------------------------


def test_bench_vault_scaling_contract():
    import bench
    out = bench.bench_vault_scaling(sizes=(64, 256), queries=6,
                                    selections=6, boot_batch=64,
                                    parity_n=45)
    assert out["vault_parity_ok"] is True
    assert out["vault_boot_speedup"] > 1.0
    assert out["boot"]["replayed_on_reopen"] == 0
    assert set(out["per_size"]) == {"64_states", "256_states"}
    for key in ("vault_coin_selection_p99_ratio", "vault_query_p99_ms",
                "sublinear_ok"):
        assert key in out
